"""Device mesh construction + shard_map'd classify (see package docstring).

Design notes (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- mesh axes: ``('flows', 'rules')`` — flows is the DP axis (batch + CT
  sharded), rules the rule-space axis (verdict rows sharded). Either may be
  size 1.
- inside the shard_map body the ONLY collectives are: one psum per counter
  (flows axis) and, when rule sharding is on, one psum for the policy cell
  (rules axis). Everything else is embarrassingly parallel — this is the
  RSS/per-CPU-map structure of the reference datapath, on ICI.
- CT sharding: the table's slot axis splits across 'flows'; each local table
  is an independent power-of-two hash table. With ``rss_mode="host"``,
  correct flow→shard placement is the HOST's job (steer_batch) — the
  direction-normalized hash guarantees a flow's forward and reply packets
  reach the same shard, so device code needs no cross-chip CT traffic at
  all. With ``rss_mode="device"`` (make_unsteered_classify_fn) rows arrive
  in plain FIFO order and the flow→shard resolution moves INTO the
  shard_map body: a ring ``ppermute`` exchange over the 'flows' axis
  (parallel/exchange.py) routes CT lookups/inserts to their owning shard —
  the host steer/scatter disappears from the hot path entirely.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from cilium_tpu.compile.ct_layout import PROBE_DEPTH
from cilium_tpu.kernels.hashing import hash_words_np
from cilium_tpu.kernels.records import BatchArrays, ct_key_words


def make_mesh(n_flow_shards: int, n_rule_shards: int = 1, devices=None):
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    need = n_flow_shards * n_rule_shards
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(n_flow_shards, n_rule_shards)
    return Mesh(arr, ("flows", "rules"))


# --------------------------------------------------------------------------- #
# Host-side steering (the RSS analog; the C++ shim implements the same hash)
# --------------------------------------------------------------------------- #
def flow_shard_of(batch: BatchArrays, n_shards: int,
                  lb=None) -> np.ndarray:
    """Direction-normalized shard index per packet: XOR of forward and
    reverse key hashes is symmetric, so both directions of a flow agree.

    ``lb`` (a compiled compile/lb.LBTables) translates service VIPs first —
    CT entries live under the DNAT'ed tuple, so steering must hash the
    translated tuple or a service flow's forward and reply packets would
    land on different CT shards. The C++ shim runs the same translation."""
    if lb is not None and lb.n_frontends:
        from cilium_tpu.compile.lb import lb_translate_np
        new_dst, new_dport, _rnat, _nb, _fe = lb_translate_np(lb, batch)
        batch = dict(batch)
        batch["dst"] = new_dst
        batch["dport"] = new_dport
    h = hash_words_np(ct_key_words(batch, reverse=False)) \
        ^ hash_words_np(ct_key_words(batch, reverse=True))
    return (h % np.uint32(n_shards)).astype(np.int32)


def steer_rows(shard: np.ndarray, n_shards: int, seg_cap: int,
               fills: Optional[np.ndarray] = None,
               counts: Optional[np.ndarray] = None) -> np.ndarray:
    """Destination row per packet for a segmented steered layout: packet i
    (shard ``shard[i]``) lands at ``shard[i]*seg_cap + fill + rank`` where
    ``rank`` preserves arrival order within the shard (stable sort) and
    ``fills`` are the segments' current occupancies (all-zero when absent).
    This is the scatter half of ``steer_batch``, shared with the pipeline's
    staging ring so flush-time steering is the same placement the classic
    steer produces. The caller checks capacity (``fills + counts`` must stay
    within ``seg_cap``); ``counts`` passes an already-computed
    ``bincount(shard, minlength=n_shards)`` so hot callers don't pay the
    histogram twice."""
    m = shard.shape[0]
    order = np.argsort(shard, kind="stable")
    sorted_s = shard[order]
    if counts is None:
        counts = np.bincount(shard, minlength=n_shards)
    counts = counts.astype(np.int64)
    starts = np.zeros(n_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rank = np.arange(m, dtype=np.int64) - starts[sorted_s]
    base = sorted_s * seg_cap + rank
    if fills is not None:
        base += np.asarray(fills, dtype=np.int64)[sorted_s]
    rows = np.empty(m, dtype=np.int64)
    rows[order] = base
    return rows


def steer_batch(batch: BatchArrays, n_shards: int,
                per_shard: Optional[int] = None, lb=None,
                round_to_pow2: bool = False,
                out: Optional[BatchArrays] = None
                ) -> Tuple[BatchArrays, np.ndarray, int]:
    """Regroup a batch so packets of shard s occupy rows
    [s*per_shard, (s+1)*per_shard) (invalid-padded).

    Returns (steered_batch, scatter_index, per_shard) where
    ``scatter_index[i]`` is the steered row of original packet i — use it to
    gather per-packet outputs back into original order.

    ``out=`` scatters into a caller-owned column dict (a reusable steered
    buffer) instead of allocating; its rows must cover
    ``n_shards * per_shard`` and every batch key must be present. Rows not
    written are restored to the empty-batch defaults, so a reused buffer
    cannot leak a previous batch's records into the valid mask or the
    wire-format probes. (The pipeline's staging ring does NOT come through
    here — it scatters incrementally at ingest via ``steer_rows``; this
    variant serves whole-batch callers that want to reuse one steered
    buffer across calls.)

    Fully vectorized (argsort regroup) — this is the host half of the
    production multi-chip path, so it must keep up with the device, not just
    the dryrun (round-4 finding: the per-packet Python loop capped steering
    at ~1e5 pps)."""
    from cilium_tpu.kernels.records import reset_batch_rows
    n = batch["valid"].shape[0]
    shard = flow_shard_of(batch, n_shards, lb=lb)
    validm = np.asarray(batch["valid"], dtype=bool)
    vidx = np.nonzero(validm)[0]
    s = shard[vidx]
    counts = np.bincount(s, minlength=n_shards).astype(np.int64)
    if per_shard is None:
        per_shard = int(max(1, counts.max()))
        if round_to_pow2:
            # stabilize the steered shape across batches (each distinct
            # n_shards*per_shard re-traces the jit): round up to a power of 2
            per_shard = 1 << (per_shard - 1).bit_length()
    elif counts.max() > per_shard:
        raise ValueError("per_shard too small for steering")
    rows = steer_rows(s, n_shards, per_shard, counts=counts)
    src = vidx
    total = n_shards * per_shard
    if out is None:
        out = {k: np.zeros((total,) + v.shape[1:], dtype=v.dtype)
               for k, v in batch.items()}
        out["http_method"][:] = 255
    else:
        if out["valid"].shape[0] < total:
            raise ValueError(
                f"steer out= buffer has {out['valid'].shape[0]} rows, "
                f"need {total}")
        reset_batch_rows(out, 0, total)
    scatter = np.full((n,), -1, dtype=np.int64)
    scatter[src] = rows
    for k, v in batch.items():
        out[k][rows] = np.asarray(v)[src]
    return out, scatter, per_shard


def unsteer_outputs(out: Dict[str, np.ndarray],
                    scatter: np.ndarray) -> Dict[str, np.ndarray]:
    """Map steered per-packet outputs back to original packet order.
    Packets that were invalid get zeros."""
    n = scatter.shape[0]
    result = {}
    safe = np.where(scatter >= 0, scatter, 0)
    for k, v in out.items():
        gathered = np.asarray(v)[safe]
        gathered[scatter < 0] = 0
        result[k] = gathered
    return result


# --------------------------------------------------------------------------- #
# Array preparation for the mesh
# --------------------------------------------------------------------------- #
def pad_snapshot_tensors(tensors: Dict[str, np.ndarray],
                         n_rule_shards: int) -> Dict[str, np.ndarray]:
    """Pad verdict id-class rows to a multiple of the rules axis. Padded rows
    are all-MISS (never gathered: id_class_of never points at them)."""
    if n_rule_shards <= 1:
        return tensors
    v = tensors["verdict"]
    rows = v.shape[2]
    padded = -(-rows // n_rule_shards) * n_rule_shards
    if padded != rows:
        pad = np.zeros((v.shape[0], v.shape[1], padded - rows, v.shape[3]),
                       dtype=v.dtype)
        tensors = dict(tensors)
        tensors["verdict"] = np.concatenate([v, pad], axis=2)
    return tensors


def shard_ct_arrays(ct: Dict[str, np.ndarray],
                    n_flow_shards: int) -> Dict[str, np.ndarray]:
    """Validate the CT capacity divides into power-of-two local tables."""
    cap = ct["expiry"].shape[0]
    local = cap // n_flow_shards
    if local * n_flow_shards != cap or (local & (local - 1)):
        raise ValueError(
            f"CT capacity {cap} must split into {n_flow_shards} "
            f"power-of-two shards")
    return ct


def degraded_ct_capacity(capacity: int, n_flow_shards: int) -> int:
    """The largest CT capacity <= ``capacity`` that still splits into
    ``n_flow_shards`` power-of-two local tables — the table geometry a
    remesh onto a NON-power-of-two survivor count rehashes into (e.g.
    4096 slots at 3 shards → 1024·3 = 3072). Healing back to a
    power-of-two width recovers the full configured capacity."""
    local = capacity // n_flow_shards
    if local < 1:
        raise ValueError(
            f"CT capacity {capacity} cannot split across "
            f"{n_flow_shards} shards")
    local = 1 << (local.bit_length() - 1)
    return local * n_flow_shards


def drop_ct_shard(arrays: Dict[str, np.ndarray], shard: int,
                  n_shards: int) -> int:
    """Zero one flow shard's slot range ``[shard*local, (shard+1)*local)``
    of a host-gathered CT table, in place. The honest-loss step of remesh
    salvage: on the CPU smoke rig a "killed" virtual device's shard is
    still physically gatherable, so salvage deliberately drops it — the
    lost shard's flows must cold-learn under the established-fingerprint
    grace window exactly as they would on real hardware. Returns the
    number of live entries dropped."""
    cap = arrays["expiry"].shape[0]
    local = cap // n_shards
    lo, hi = shard * local, (shard + 1) * local
    n_live = int((arrays["expiry"][lo:hi] > 0).sum())
    for k, v in arrays.items():
        v[lo:hi] = 0
    return n_live


def _reverse_key_words(keys: np.ndarray) -> np.ndarray:
    """[M,10] forward CT key words → reverse orientation (addr/port swap,
    direction flip) — the host inverse of records.ct_key_words(reverse)."""
    rev = keys.copy()
    rev[:, 0:4] = keys[:, 4:8]
    rev[:, 4:8] = keys[:, 0:4]
    rev[:, 8] = ((keys[:, 8] << np.uint32(16))
                 | (keys[:, 8] >> np.uint32(16)))
    rev[:, 9] = ((keys[:, 9] & np.uint32(0xFFFFFF00))
                 | (np.uint32(1) - (keys[:, 9] & np.uint32(0xFF))))
    return rev


def rehash_ct_arrays(arrays: Dict[str, np.ndarray], n_flow_shards: int,
                     probe_depth: int = PROBE_DEPTH,
                     capacity: Optional[int] = None
                     ) -> Tuple[Dict[str, np.ndarray], int]:
    """Re-place every live CT entry at the open-addressed position the device
    probe expects for the given shard layout (shard = direction-normalized
    hash, local slot = key hash mod the per-shard table, linear probe).

    Checkpoint portability: an exported table's slot placement is only valid
    for the geometry that wrote it (the bounded oracle-backed fake and a
    single-chip table hash over the FULL capacity; a sharded table hashes
    per shard — and legacy fake exports were dense-from-0). Rehashing on
    import makes restore correct across backends and shard counts. Returns
    (new_arrays, n_dropped) — entries whose probe window is exhausted are
    dropped (counted; a restore-time drop means the flow re-learns as NEW
    on its next packet — unlike a live insert exhaustion, which since the
    insert-when-full contract fails CLOSED with DROP ``CT_FULL``).
    ``capacity`` resizes the table while rehashing (checkpoint restored into
    a backend configured with a different ct_capacity).
    """
    cap = int(capacity or arrays["expiry"].shape[0])
    local = cap // n_flow_shards
    if local * n_flow_shards != cap or (local & (local - 1)):
        raise ValueError(
            f"CT capacity {cap} must split into {n_flow_shards} "
            f"power-of-two shards")
    live = np.nonzero(arrays["expiry"] > 0)[0]
    m = live.shape[0]
    keys = arrays["keys"][live].astype(np.uint32)
    fwd_h = hash_words_np(keys)
    shard = ((fwd_h ^ hash_words_np(_reverse_key_words(keys)))
             % np.uint32(n_flow_shards)).astype(np.int64)
    home = (fwd_h & np.uint32(local - 1)).astype(np.int64)
    base = shard * local

    new = {k: np.zeros((cap,) + v.shape[1:], dtype=v.dtype)
           for k, v in arrays.items()}
    occupied = np.zeros(cap, dtype=bool)
    placed_slot = np.full(m, -1, dtype=np.int64)
    pending = np.ones(m, dtype=bool)
    idx = np.arange(m, dtype=np.int64)
    for r in range(probe_depth):
        t = base + ((home + r) & (local - 1))
        attempt = pending & ~occupied[t]
        claim = np.full(cap + 1, m, dtype=np.int64)
        np.minimum.at(claim, np.where(attempt, t, cap), idx)
        winner = attempt & (claim[t] == idx)
        occupied[t[winner]] = True
        placed_slot[winner] = t[winner]
        pending = pending & ~winner
    ok = placed_slot >= 0
    src, dst = live[ok], placed_slot[ok]
    for k in arrays:
        new[k][dst] = arrays[k][src]
    return new, int(pending.sum())


# --------------------------------------------------------------------------- #
# The meshed classify step
# --------------------------------------------------------------------------- #
def make_sharded_classify_fn(mesh, probe_depth: int = PROBE_DEPTH,
                             v4_only: bool = False, donate_ct: bool = True,
                             fused: bool = False,
                             fused_interpret: bool = False):
    """shard_map'd + jitted classify step over ``mesh`` ('flows','rules').

    ``fused``/``fused_interpret`` route each shard's classify interior
    through the Pallas megakernels (kernels/fused.py) exactly like the
    single-chip ``make_classify_fn`` — the kernels run on per-shard local
    arrays inside the shard_map body, so the mesh geometry is unchanged.
    With rule sharding the policy/L7 stage stays on the jnp reference (its
    psum must remain in the shard_map body); LPM and the CT probe pair
    still fuse per shard.

    Call with (tensors, ct, batch, now, world_index) where batch rows are
    steered (steer_batch) and verdict rows padded (pad_snapshot_tensors).

    ``batch`` may be the column dict (tests, the zero-copy-disabled path)
    OR a packed wire — a single [N, words] uint32 array or an
    ``(wire, path_dict)`` L7-dict pair (kernels/records pack formats, the
    same contiguous-buffer transfer the single-chip path ships). The wire
    rows shard over 'flows' (each chip unpacks only its own segment, fused
    into the classify pipeline); the path dict replicates. This is what
    lets the sharded serving path pack in place into one pooled buffer
    whose per-shard segments ARE the per-chip transfers.
    """
    from cilium_tpu.kernels.classify import classify_step

    rule_axis = "rules" if mesh.shape["rules"] > 1 else None

    def body(tensors, ct, batch, now, world_index):
        return classify_step(
            tensors, ct, batch, now, world_index,
            probe_depth=probe_depth, v4_only=v4_only, rule_axis=rule_axis,
            fused=fused, fused_interpret=fused_interpret)

    return _make_meshed_classify(mesh, body, donate_ct=donate_ct)


def make_unsteered_classify_fn(mesh, probe_depth: int = PROBE_DEPTH,
                               v4_only: bool = False, donate_ct: bool = True,
                               fused: bool = False,
                               fused_interpret: bool = False):
    """shard_map'd + jitted DEVICE-RSS classify step over ``mesh``
    ('flows','rules'): batch rows shard over 'flows' in plain ARRIVAL
    order — no host steering, no placement semantics in the row layout —
    and cross-shard CT lookups/inserts resolve with the ring ``ppermute``
    exchange (parallel/exchange.py) inside the shard_map body. Outputs
    come back in the same arrival row order (FIFO — no un-steer gather
    anywhere), bit-identical to what the steered path computes for the
    same rows, CT_FULL tail-evict order included (the gathered request
    set preserves global row order, and the owner-side CT stage IS the
    steered path's ct_update_stage).

    The collective set inside the body stays bounded and documented: the
    counter psum over 'flows' (+ the policy-cell psum over 'rules' when
    rule-sharded) plus the 2(n-1) ring ppermute hops of the exchange.
    ``fused`` honors the LPM and CT-probe Pallas kernels; the policy
    stage runs the split jnp core (see classify_step_exchange). The only
    shape contract: batch rows must divide the 'flows' axis (each chip
    takes an equal arrival-order slice).

    Accepts the same batch forms as :func:`make_sharded_classify_fn`
    (column dict, packed wire, (wire, path_dict))."""
    from cilium_tpu.parallel.exchange import classify_step_exchange

    n_flow = mesh.shape["flows"]
    rule_axis = "rules" if mesh.shape["rules"] > 1 else None

    def body(tensors, ct, batch, now, world_index):
        return classify_step_exchange(
            tensors, ct, batch, now, world_index,
            axis_name="flows", n_shards=n_flow,
            probe_depth=probe_depth, v4_only=v4_only, rule_axis=rule_axis,
            fused=fused, fused_interpret=fused_interpret)

    return _make_meshed_classify(mesh, body, donate_ct=donate_ct)


def _make_meshed_classify(mesh, body, donate_ct: bool = True):
    """The shared shard_map/jit plumbing behind both meshed classify
    variants: spec construction, the per-(tensor-key-set, batch-kind) jit
    cache, device-side wire unpack, and the counter psum."""
    import jax
    try:
        from jax import shard_map
    except ImportError:                 # jax < 0.6: experimental location
        from jax.experimental.shard_map import shard_map
    import inspect
    # the replication-check kwarg was renamed check_rep → check_vma
    _check_kw = ("check_vma"
                 if "check_vma" in inspect.signature(shard_map).parameters
                 else "check_rep")
    from jax.sharding import PartitionSpec as P

    rule_sharded = mesh.shape["rules"] > 1

    def local_fn(tensors, ct, batch, now, world_index):
        out, new_ct, counters = body(tensors, ct, batch, now, world_index)
        # counters are global: reduce over 'flows' only — along 'rules' the
        # batch is replicated and every shard computes identical counts
        # (summing there would multiply by the rules-axis size)
        counters = {
            "by_reason_dir": jax.lax.psum(counters["by_reason_dir"], "flows"),
            "insert_fail": jax.lax.psum(counters["insert_fail"], "flows"),
            "ct_evicted": jax.lax.psum(counters["ct_evicted"], "flows"),
        }
        return out, new_ct, counters

    verdict_spec = P(None, None, "rules", None) if rule_sharded else P()
    ct_spec = {k: P("flows") for k in
               ("keys", "expiry", "created", "flags", "pkts_fwd", "pkts_rev",
                "rev_nat")}
    batch_spec = {k: P("flows") for k in
                  ("src", "dst", "sport", "dport", "proto", "tcp_flags",
                   "is_v6", "ep_slot", "direction", "http_method",
                   "http_path", "valid")}
    out_spec = {k: P("flows") for k in
                ("allow", "reason", "status", "ct_full", "remote_identity",
                 "redirect", "matched_rule", "lpm_prefix", "ct_state_pre",
                 "svc", "nat_dst", "nat_dport", "rnat",
                 "rnat_src", "rnat_sport")}
    counters_spec = {"by_reason_dir": P(), "insert_fail": P(),
                     "ct_evicted": P()}

    def local_fn_packed(tensors, ct, wire, now, world_index):
        # device-side unpack of the local wire segment; the width dispatch
        # happens at trace time exactly like make_classify_fn(packed=True)
        from cilium_tpu.kernels.records import unpack_wire_jnp
        return local_fn(tensors, ct, unpack_wire_jnp(wire), now, world_index)

    # The snapshot's tensor key-set varies (LB tensors are elided when no
    # frontend exists), and shard_map in_specs must mirror the exact pytree —
    # so build + cache one shard_map'd jit per (key-set, batch kind).
    # Everything except the verdict is replicated (LB state included: small,
    # read-only, gathered per packet).
    jits: Dict[Any, Any] = {}

    def call(tensors, ct, batch, now, world_index):
        if isinstance(batch, dict):
            kind = "dict"
        elif isinstance(batch, (tuple, list)):
            batch = tuple(batch)
            kind = f"wire_dict{len(batch)}"
        else:
            kind = "wire"
        key = (frozenset(tensors), kind)
        fn = jits.get(key)
        if fn is None:
            tensors_spec = {k: (verdict_spec if k == "verdict" else P())
                            for k in tensors}
            if kind == "dict":
                bspec: Any = batch_spec
                body = local_fn
            else:
                # wire rows shard over 'flows'; every trailing dictionary
                # ((wire, path_dict) or (wire, addr_dict, path_dict))
                # replicates — the spec mirrors the tuple arity
                bspec = (P("flows"),) + (P(),) * (len(batch) - 1) \
                    if kind.startswith("wire_dict") else P("flows")
                body = local_fn_packed
            fn = jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(tensors_spec, ct_spec, bspec, P(), P()),
                out_specs=(out_spec, ct_spec, counters_spec),
                **{_check_kw: False},
            ), donate_argnums=(1,) if donate_ct else ())
            jits[key] = fn
        return fn(tensors, ct, batch, now, world_index)

    return call
