"""Device-side RSS: the in-kernel ICI ring ``ppermute`` CT exchange.

The steered serving path (parallel/mesh.py) pays a host tax on every
batch: rows are pre-binned in the feeder, scattered into per-shard staging
segments, and MUST land on their CT shard before dispatch — the eBPF
datapath's per-CPU RSS analog implemented in Python. This module is the
device-side alternative SURVEY §5 names: each chip classifies whatever
rows arrive on it (arrival order, no placement semantics), computes the
flow→shard hash on-device, and resolves cross-shard CT lookups/inserts
with a ring ``ppermute`` over the ``flows`` axis.

The exchange is two static ring phases around one owner-side CT stage:

1. **request gather** (``ring_all_gather``, n-1 hops): every chip's local
   request buffer — the post-DNAT forward CT keys plus the few bits the CT
   stage needs (tcp_flags, validity, the would-be allow for hit/new rows,
   the rev-NAT id to record) packed into one fixed-shape ``[L, REQ_WORDS]``
   uint32 array — rotates around the ring, so after n-1 neighbor hops every
   chip holds all n chips' requests indexed by origin. Flattened in origin
   order, the gathered rows ARE the bucket's global row order, which is
   what keeps the insert conflict/tail-evict resolution bit-identical to
   the steered path (relative order within a shard is arrival order in
   both layouts).
2. **owner-side CT stage** (``ct_exchange_serve``): each chip masks the
   gathered rows to the flows whose direction-normalized hash makes THIS
   shard their home, probes both orientations against its local table
   (the rev-CT probe rides the same exchange — each leg's key travels
   explicitly, so asymmetric DSR/NAT legs whose forward and reverse
   orientations hash to different chips are expressible by masking each
   probe by its own key's home; today's symmetric hash makes the two homes
   coincide, which is exactly what keeps device mode bit-identical to host
   steering), and runs the SAME insert-when-full + aggregate-apply stage
   (kernels/classify.ct_update_stage) the steered path runs — one source
   of the CT mutation semantics, including CT_FULL tail-evict order.
3. **reply scatter** (``ring_reduce_scatter``, n-1 hops): each owner's
   replies — est/reply/ct_full bits + the batch-start rev-NAT id, masked
   to the rows it owns — ride home as ``[n, L, REP_WORDS]`` chunks that
   accumulate around the ring (each row has exactly one owner, so the sum
   is a routing, not a reduction).

Everything else — LB/DNAT, the LPM walk, the policy ladder, L7, verdict
composition, the counters — runs locally on the arrival chip via the
shared cores in kernels/classify.py (classify_pre_ct / compose_verdict /
resolve_rev_nat), so the shard_map body's collective set stays bounded:
the existing counter/rules psums plus these 2(n-1) ring ppermute hops.
No host round-trips inside the classify step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import PROBE_DEPTH
from cilium_tpu.kernels import conntrack as ctk
from cilium_tpu.kernels.classify import (classify_pre_ct, compose_verdict,
                                         ct_update_stage, resolve_rev_nat,
                                         tally_by_reason_dir)
from cilium_tpu.kernels.hashing import hash_words_jnp
from cilium_tpu.utils import constants as C

#: request row layout ([L, REQ_WORDS] uint32): words 0..9 = the post-DNAT
#: forward CT key, 10 = tcp_flags, 11 = meta bits (valid | allow_if_hit<<1
#: | allow_if_new<<2), 12 = the rev-NAT id to record on a fresh insert
REQ_WORDS = 13
#: reply row layout ([L, REP_WORDS] uint32): word 0 = est | reply<<1 |
#: ct_full<<2, word 1 = the batch-start CT entry rev-NAT id at the hit slot
REP_WORDS = 2


def exchange_bytes(rows: int, n_shards: int) -> int:
    """Worst-case per-mesh bytes the exchange materializes for one
    ``rows``-row bucket: every chip holds the full gathered request set
    [n, L, REQ] plus the travelling reply chunks [n, L, REP] — the number
    the HBM ledger's ``exchange`` group and the ``rss_exchange`` resource
    row report."""
    return n_shards * rows * (REQ_WORDS + REP_WORDS) * 4


def flow_shard_of_keys(fwd_keys, rev_keys, n_shards: int):
    """Direction-normalized shard index per key pair — the device twin of
    parallel/mesh.flow_shard_of's hash (XOR of forward and reverse key
    hashes is symmetric, so both directions of a flow agree), over the
    already-DNAT-translated keys. Bit-identical to the host steer by the
    shared hash_words implementation."""
    h = hash_words_jnp(fwd_keys) ^ hash_words_jnp(rev_keys)
    return (h % jnp.uint32(n_shards)).astype(jnp.int32)


# --------------------------------------------------------------------------- #
# The ring primitives (explicit ppermute hops — the static ICI schedule)
# --------------------------------------------------------------------------- #
def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_all_gather(x, axis_name: str, n: int):
    """[L, W] per chip → [n, L, W] indexed by ORIGIN chip, via n-1 ring
    ``ppermute`` hops (one neighbor hop per step). ``jax.lax.all_gather``
    would lower to the same ring on ICI; the explicit form keeps the
    collective set auditable — the shard_map body provably contains
    nothing but psums and these hops."""
    if n == 1:
        return x[None]
    my = jax.lax.axis_index(axis_name)
    perm = _ring_perm(n)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = jax.lax.dynamic_update_index_in_dim(out, x, my, 0)
    buf = x
    for t in range(1, n):
        buf = jax.lax.ppermute(buf, axis_name, perm)
        # after t forward hops this chip holds the buffer that ORIGINATED
        # t positions behind it on the ring
        out = jax.lax.dynamic_update_index_in_dim(
            out, buf, jnp.mod(my - t, n), 0)
    return out


def ring_reduce_scatter(parts, axis_name: str, n: int):
    """[n, L, W] per chip (chunk c = this chip's contribution to chip c's
    rows) → [L, W]: chunk c starts at chip c+1, accumulates every chip's
    contribution over n-1 ring hops, and arrives home summed. With each
    row owned by exactly one shard (the exchange's reply masking) the sum
    is pure routing — disjoint writers, no actual reduction."""
    if n == 1:
        return parts[0]
    my = jax.lax.axis_index(axis_name)
    perm = _ring_perm(n)
    acc = jax.lax.dynamic_index_in_dim(parts, jnp.mod(my - 1, n), 0,
                                       keepdims=False)
    for t in range(1, n):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + jax.lax.dynamic_index_in_dim(
            parts, jnp.mod(my - 1 - t, n), 0, keepdims=False)
    return acc


# --------------------------------------------------------------------------- #
# Exchange buffer packing (fixed shapes → static collective schedule)
# --------------------------------------------------------------------------- #
def pack_requests(fwd_keys, tcp_flags, valid, allow_if_hit, allow_if_new,
                  rev_nat_vals):
    """→ [L, REQ_WORDS] uint32 (layout at the module constants)."""
    meta = (valid.astype(jnp.uint32)
            | (allow_if_hit.astype(jnp.uint32) << jnp.uint32(1))
            | (allow_if_new.astype(jnp.uint32) << jnp.uint32(2)))
    return jnp.concatenate([
        fwd_keys.astype(jnp.uint32),
        tcp_flags.astype(jnp.uint32)[:, None],
        meta[:, None],
        rev_nat_vals.astype(jnp.uint32)[:, None],
    ], axis=-1)


def unpack_requests(req):
    fwd_keys = req[:, :10]
    tcp_flags = req[:, 10].astype(jnp.int32)
    meta = req[:, 11]
    valid = (meta & jnp.uint32(1)) != 0
    allow_if_hit = (meta & jnp.uint32(2)) != 0
    allow_if_new = (meta & jnp.uint32(4)) != 0
    rev_nat_vals = req[:, 12].astype(jnp.int32)
    return fwd_keys, tcp_flags, valid, allow_if_hit, allow_if_new, \
        rev_nat_vals


def pack_replies(est, reply, ct_full, entry_rnat, mine):
    """→ [G, REP_WORDS] uint32, masked to the rows THIS shard owns so the
    homeward reduce-scatter has exactly one writer per row."""
    flags = (est.astype(jnp.uint32)
             | (reply.astype(jnp.uint32) << jnp.uint32(1))
             | (ct_full.astype(jnp.uint32) << jnp.uint32(2)))
    rnat = jnp.where(mine, entry_rnat.astype(jnp.uint32), jnp.uint32(0))
    return jnp.stack([flags, rnat], axis=-1)


def unpack_replies(rep):
    flags = rep[:, 0]
    est = (flags & jnp.uint32(1)) != 0
    reply = (flags & jnp.uint32(2)) != 0
    ct_full = (flags & jnp.uint32(4)) != 0
    entry_rnat = rep[:, 1].astype(jnp.int32)
    return est, reply, ct_full, entry_rnat


# --------------------------------------------------------------------------- #
# The owner-side CT stage
# --------------------------------------------------------------------------- #
def ct_exchange_serve(ct, req_flat, axis_name: str, n_shards: int, now,
                      probe_depth: int = PROBE_DEPTH, plan=None,
                      fused_interpret: bool = False):
    """Serve the gathered request set against THIS chip's local CT shard:
    probe pair → est/reply/new → insert-when-full → aggregate apply →
    batch-start rev-NAT read — the exact CT stage classify_step runs,
    over exactly the rows whose flow hash homes here, in global bucket
    row order (origin-major). Foreign rows are valid-masked out; their
    keys can never match this shard's entries anyway (flows only insert
    at their home), so hit sets, protected slots and eviction victims are
    identical to the steered layout's.

    → (rep [G, REP_WORDS] uint32 — replies masked to owned rows,
    new_ct, insert_fail uint32 scalar, n_evicted uint32 scalar)."""
    fwd_keys, tcp_flags, valid, allow_if_hit, allow_if_new, rev_nat_vals = \
        unpack_requests(req_flat)
    rev_keys = ctk.reverse_key_words_jnp(fwd_keys)
    my = jax.lax.axis_index(axis_name)
    # each probe leg routes by its own key pair's home; the symmetric hash
    # makes the forward and reverse orientations agree, so one mask serves
    # both probes (an asymmetric DSR hash would split this into per-leg
    # masks — the schedule would not change)
    mine = flow_shard_of_keys(fwd_keys, rev_keys, n_shards) == my
    valid = valid & mine

    if plan is not None and plan.ct:
        from cilium_tpu.kernels import fused as fk
        fwd_slot, rev_slot = fk.ct_probe_pair_fused(
            ct, fwd_keys, rev_keys, now, probe_depth,
            interpret=fused_interpret)
    else:
        fwd_slot = ctk.ct_probe(ct, fwd_keys, now, probe_depth)
        rev_slot = ctk.ct_probe(ct, rev_keys, now, probe_depth)
    est = valid & (fwd_slot >= 0)
    reply = valid & ~est & (rev_slot >= 0)
    new = valid & ~est & ~reply
    hit = est | reply
    hit_slot = jnp.where(est, fwd_slot, jnp.where(reply, rev_slot, 0))
    # the would-be allow the origin chip composed without est/reply: pick
    # the branch the probe resolved (foreign rows are gated by new=False /
    # hit=False, so their value is irrelevant)
    allow = jnp.where(hit, allow_if_hit, allow_if_new)

    proto = (fwd_keys[:, 9] >> jnp.uint32(8)).astype(jnp.int32)
    new_ct, ct_full, entry_rnat, n_evicted = ct_update_stage(
        ct, fwd_keys, proto, tcp_flags, hit, hit_slot, reply, new, allow,
        rev_nat_vals, now, probe_depth)
    rep = pack_replies(est, reply, ct_full, entry_rnat, mine)
    return rep, new_ct, ct_full.sum().astype(jnp.uint32), n_evicted


# --------------------------------------------------------------------------- #
# The unsteered classify step (runs inside the shard_map body)
# --------------------------------------------------------------------------- #
def classify_step_exchange(tensors, ct, batch, now, world_index=0, *,
                           axis_name: str = "flows", n_shards: int,
                           probe_depth: int = PROBE_DEPTH,
                           v4_only: bool = False, rule_axis=None,
                           lb_probe_depth: int = 8, fused: bool = False,
                           fused_interpret: bool = False):
    """→ (out, new_ct, counters) — the device-RSS twin of
    kernels/classify.classify_step over THIS chip's arrival-order rows.

    Structure: the shared pre-CT stage (LB → LPM → split interior) runs
    locally, the CT stage resolves through the ring ppermute exchange
    (module docstring), and the verdict composes locally from the replies
    — every semantic block is the same shared core the steered path runs,
    so bit-identity holds by construction. ``fused`` honors the LPM and
    CT-probe Pallas kernels (fuse_plan); the policy stage always runs the
    split jnp core here — the fused interior composes est/reply inside
    one kernel, which cannot straddle the exchange."""
    if fused:
        from cilium_tpu.kernels import fused as fk
        plan = fk.fuse_plan(tensors, ct, v4_only=v4_only,
                            rule_axis=rule_axis)
    else:
        plan = None
    pre = classify_pre_ct(tensors, batch, world_index, v4_only=v4_only,
                          rule_axis=rule_axis, lb_probe_depth=lb_probe_depth,
                          plan=plan, fused_interpret=fused_interpret,
                          split_interior=True)
    b = pre["batch"]
    valid = pre["valid"]
    direction = b["direction"]
    no_backend = pre["no_backend"]

    # the would-be allow for each probe outcome, composed through the one
    # shared compose_verdict (est/reply pinned) so the owner's insert
    # decision can never drift from the verdict the origin composes later
    ones = jnp.ones_like(valid)
    zeros = jnp.zeros_like(valid)
    allow_if_hit = compose_verdict(
        pre["decision"], pre["enforced"], pre["cell_redirect"],
        pre["l7_fail"], ones, zeros, valid)[0]
    allow_if_new = compose_verdict(
        pre["decision"], pre["enforced"], pre["cell_redirect"],
        pre["l7_fail"], zeros, zeros, valid)[0]

    req = pack_requests(pre["fwd_keys"], b["tcp_flags"], valid,
                        allow_if_hit, allow_if_new, pre["rev_nat"])
    local_rows = req.shape[0]
    gathered = ring_all_gather(req, axis_name, n_shards)
    rep_all, new_ct, insert_fail, n_evicted = ct_exchange_serve(
        ct, gathered.reshape(n_shards * local_rows, REQ_WORDS),
        axis_name, n_shards, now, probe_depth, plan=plan,
        fused_interpret=fused_interpret)
    rep = ring_reduce_scatter(
        rep_all.reshape(n_shards, local_rows, REP_WORDS), axis_name,
        n_shards)
    est, reply, ct_full, entry_rnat = unpack_replies(rep)

    # local verdict composition from the replies — the same 3-5 → 6b → 7
    # tail classify_step runs
    allow, reason, status, redirect = compose_verdict(
        pre["decision"], pre["enforced"], pre["cell_redirect"],
        pre["l7_fail"], est, reply, valid)
    matched_rule = jnp.where(valid & pre["enforced"], pre["mrule"],
                             jnp.int32(-1)).astype(jnp.int32)
    reason = jnp.where(no_backend, int(C.DropReason.NO_SERVICE), reason)
    allow = allow & ~ct_full
    reason = jnp.where(ct_full, int(C.DropReason.CT_FULL), reason)
    rnat, rnat_src, rnat_sport = resolve_rev_nat(
        tensors, entry_rnat, reply, b["src"], b["sport"])
    counted = valid | no_backend
    by_reason_dir = tally_by_reason_dir(reason, direction, counted)
    counters = {
        "by_reason_dir": by_reason_dir,
        # owner-side totals: each chip counts the gathered rows IT served;
        # the caller's psum over 'flows' yields the same global totals the
        # steered layout's per-chip sums produce
        "insert_fail": insert_fail,
        "ct_evicted": n_evicted,
    }
    out = {
        "allow": allow,
        "reason": reason,
        "status": status,
        "ct_full": ct_full,
        "remote_identity": pre["remote_identity"],
        "redirect": redirect,
        "matched_rule": matched_rule,
        "lpm_prefix": pre["lpm_prefix"],
        "ct_state_pre": status,
        "svc": pre["svc"] & valid,
        "nat_dst": b["dst"],
        "nat_dport": b["dport"].astype(jnp.int32),
        "rnat": rnat,
        "rnat_src": rnat_src,
        "rnat_sport": rnat_sport,
    }
    return out, new_ct, counters
