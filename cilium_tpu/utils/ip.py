"""IP address / prefix helpers.

All addresses are normalized to 16 bytes: IPv6 verbatim, IPv4 as the
v4-mapped form ``::ffff:a.b.c.d``. This lets a single 16-level stride-8 LPM
trie serve both families (SURVEY.md §5 "long-context" analog: LPM over 100k
prefixes as multi-level stride tables), with a precomputed 4-level fast path
for pure-IPv4 batches.
"""

from __future__ import annotations

import ipaddress
from typing import Tuple

V4_MAPPED_PREFIX = b"\x00" * 10 + b"\xff\xff"


def parse_addr(text: str) -> Tuple[bytes, bool]:
    """Parse an address string → (16-byte normalized form, is_ipv6)."""
    addr = ipaddress.ip_address(text)
    if addr.version == 4:
        return V4_MAPPED_PREFIX + addr.packed, False
    return addr.packed, True


def parse_prefix(text: str) -> Tuple[bytes, int, bool]:
    """Parse a CIDR string → (16-byte normalized network address, normalized
    prefix length in the 128-bit space, is_ipv6).

    IPv4 ``/p`` becomes ``/(96+p)`` in the v4-mapped space.
    """
    net = ipaddress.ip_network(text, strict=False)
    if net.version == 4:
        return V4_MAPPED_PREFIX + net.network_address.packed, 96 + net.prefixlen, False
    return net.network_address.packed, net.prefixlen, True


def normalize_prefix(text: str) -> str:
    """Canonical string form of a CIDR (host bits cleared)."""
    return str(ipaddress.ip_network(text, strict=False))


def addr_to_words(addr16: bytes) -> Tuple[int, int, int, int]:
    """16-byte address → four big-endian uint32 words (device layout)."""
    return (
        int.from_bytes(addr16[0:4], "big"),
        int.from_bytes(addr16[4:8], "big"),
        int.from_bytes(addr16[8:12], "big"),
        int.from_bytes(addr16[12:16], "big"),
    )


def words_to_addr(words) -> bytes:
    return b"".join(int(w).to_bytes(4, "big") for w in words)


def addr_to_str(addr16: bytes) -> str:
    """Render a normalized 16-byte address, un-mapping v4."""
    if addr16[:12] == V4_MAPPED_PREFIX:
        return str(ipaddress.IPv4Address(addr16[12:]))
    return str(ipaddress.IPv6Address(addr16))


def is_v4_mapped(addr16: bytes) -> bool:
    return addr16[:12] == V4_MAPPED_PREFIX
