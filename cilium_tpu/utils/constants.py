"""Shared constants: reserved identities, protocols, verdicts, drop reasons, CT.

Numbering follows upstream Cilium's public, documented values where those are
well-known (reserved identities, local-identity scope bit). Drop-reason numbers
are this framework's own enum — the *names* mirror upstream's
``bpf/lib/drop.h`` reason names, but the reference mount was empty (SURVEY.md
§0) so no numeric values are claimed as read-from-source.
"""

from __future__ import annotations

import enum

# --------------------------------------------------------------------------- #
# Reserved security identities (upstream: pkg/identity/reserved, numericidentity)
# --------------------------------------------------------------------------- #
IDENTITY_UNKNOWN = 0
IDENTITY_HOST = 1
IDENTITY_WORLD = 2
IDENTITY_UNMANAGED = 3
IDENTITY_HEALTH = 4
IDENTITY_INIT = 5
IDENTITY_REMOTE_NODE = 6
IDENTITY_KUBE_APISERVER = 7
IDENTITY_INGRESS = 8

RESERVED_IDENTITIES = {
    "unknown": IDENTITY_UNKNOWN,
    "host": IDENTITY_HOST,
    "world": IDENTITY_WORLD,
    "unmanaged": IDENTITY_UNMANAGED,
    "health": IDENTITY_HEALTH,
    "init": IDENTITY_INIT,
    "remote-node": IDENTITY_REMOTE_NODE,
    "kube-apiserver": IDENTITY_KUBE_APISERVER,
    "ingress": IDENTITY_INGRESS,
}
RESERVED_IDENTITY_NAMES = {v: k for k, v in RESERVED_IDENTITIES.items()}

# First identity id available for cluster-scope (label-derived) identities.
CLUSTER_IDENTITY_BASE = 256
# Cluster-scope identities fit in 16 bits upstream.
CLUSTER_IDENTITY_MAX = 65535

# Node-local identities (CIDR-derived) carry the local scope bit
# (upstream: identity.IdentityScopeLocal == 1 << 24).
LOCAL_IDENTITY_SCOPE = 1 << 24

# Wildcard identity in MapState / policymap keys (matches any remote identity).
IDENTITY_ANY = 0

# --------------------------------------------------------------------------- #
# Protocols
# --------------------------------------------------------------------------- #
PROTO_ANY = 0
PROTO_ICMP = 1
PROTO_TCP = 6
PROTO_UDP = 17
PROTO_ICMP6 = 58
PROTO_SCTP = 132

PROTO_NAMES = {
    PROTO_ANY: "ANY",
    PROTO_ICMP: "ICMP",
    PROTO_TCP: "TCP",
    PROTO_UDP: "UDP",
    PROTO_ICMP6: "ICMPv6",
    PROTO_SCTP: "SCTP",
}
PROTO_BY_NAME = {v: k for k, v in PROTO_NAMES.items()}

# Protocols that carry L4 ports.
PORT_PROTOS = (PROTO_TCP, PROTO_UDP, PROTO_SCTP)

# Dense "proto family" index used by the compiled tensors: ports only make
# sense for TCP/UDP/SCTP; ICMP type is carried in the port field (upstream CT
# does the same trick with ICMP type/code in the port slots). ICMP and ICMPv6
# are distinct families so their entries never shadow each other's cells.
PROTO_FAMILY_TCP = 0
PROTO_FAMILY_UDP = 1
PROTO_FAMILY_SCTP = 2
PROTO_FAMILY_ICMP = 3
PROTO_FAMILY_ICMP6 = 4
PROTO_FAMILY_OTHER = 5
N_PROTO_FAMILIES = 6


def proto_family(proto: int) -> int:
    if proto == PROTO_TCP:
        return PROTO_FAMILY_TCP
    if proto == PROTO_UDP:
        return PROTO_FAMILY_UDP
    if proto == PROTO_SCTP:
        return PROTO_FAMILY_SCTP
    if proto == PROTO_ICMP:
        return PROTO_FAMILY_ICMP
    if proto == PROTO_ICMP6:
        return PROTO_FAMILY_ICMP6
    return PROTO_FAMILY_OTHER


# --------------------------------------------------------------------------- #
# Directions (relative to the local endpoint, as in per-endpoint policymaps)
# --------------------------------------------------------------------------- #
DIR_EGRESS = 0   # traffic leaving the endpoint
DIR_INGRESS = 1  # traffic entering the endpoint
N_DIRECTIONS = 2

DIR_NAMES = {DIR_EGRESS: "egress", DIR_INGRESS: "ingress"}

# --------------------------------------------------------------------------- #
# Verdict codes (dense tensor cell values; low 2 bits = decision)
# --------------------------------------------------------------------------- #
VERDICT_MISS = 0       # no matching entry: default-deny if enforced else allow
VERDICT_ALLOW = 1
VERDICT_DENY = 2
VERDICT_REDIRECT = 3   # L7 redirect; upper bits carry the L7 ruleset id

VERDICT_DECISION_MASK = 0x3
VERDICT_L7_SHIFT = 2   # l7 ruleset id stored in bits [2..15] of the uint16 cell


def verdict_cell(decision: int, l7_id: int = 0) -> int:
    return (l7_id << VERDICT_L7_SHIFT) | decision


# --------------------------------------------------------------------------- #
# Final per-packet forward decision + drop reasons.
# Names mirror upstream bpf/lib/drop.h; numbers are ours (see module docstring).
# --------------------------------------------------------------------------- #
class DropReason(enum.IntEnum):
    OK = 0                    # forwarded
    POLICY = 130              # default deny: enforced direction, no matching rule
    POLICY_DENY = 133         # explicit deny rule matched
    POLICY_L7 = 180           # L7-lite rules matched none of the request tokens
    CT_INVALID = 134          # malformed / untrackable (e.g. bad header record)
    INVALID_IDENTITY = 135    # ipcache produced no usable identity
    UNSUPPORTED_PROTO = 136
    CT_FULL = 137             # new flow: CT probe window saturated with
    #                           unevictable entries (adversarial-load fail
    #                           closed; upstream analog: CT map insert failed)
    NO_SERVICE = 140          # dst matched a service frontend with no backends


# Geometry of the per-batch verdict counters tensor (kernels/classify.py
# accumulates drops by reason x direction in-kernel; runtime/metrics.py
# aggregates the same shape on the host). Reason ids are an 8-bit field.
DROP_REASON_BINS = 256
COUNTER_CELLS = DROP_REASON_BINS * N_DIRECTIONS

if int(max(DropReason)) >= DROP_REASON_BINS:
    raise AssertionError(
        "DropReason value exceeds DROP_REASON_BINS — widen the counter "
        "tensor geometry before adding reasons past the 8-bit field")


# --------------------------------------------------------------------------- #
# Conntrack (upstream: bpf/lib/conntrack.h, pkg/maps/ctmap)
# --------------------------------------------------------------------------- #
class CTStatus(enum.IntEnum):
    NEW = 0
    ESTABLISHED = 1
    REPLY = 2
    # RELATED (ICMP errors referencing an inner tuple) is deliberately not
    # implemented in v1; ICMP echo is tracked as its own flow instead.


# Lifetimes in seconds (upstream defaults: CT_SYN_TIMEOUT 60s,
# CT_ESTABLISHED_LIFETIME_TCP 21600s, nonTCP 60s, CT_CLOSE_TIMEOUT 10s).
CT_LIFETIME_SYN = 60
CT_LIFETIME_TCP = 21600
CT_LIFETIME_NONTCP = 60
CT_LIFETIME_CLOSE = 10

# CT entry flag bits.
CT_FLAG_SEEN_NON_SYN = 1 << 0
CT_FLAG_TX_CLOSING = 1 << 1
CT_FLAG_RX_CLOSING = 1 << 2

# TCP header flag bits (standard wire format, low byte).
TCP_FIN = 0x01
TCP_SYN = 0x02
TCP_RST = 0x04
TCP_PSH = 0x08
TCP_ACK = 0x10

# --------------------------------------------------------------------------- #
# Policy enforcement modes (upstream: option.Config.EnablePolicy —
# "default" | "always" | "never"; these change verdicts, so they are part of
# the parity contract)
# --------------------------------------------------------------------------- #
ENFORCEMENT_DEFAULT = "default"
ENFORCEMENT_ALWAYS = "always"
ENFORCEMENT_NEVER = "never"
ENFORCEMENT_MODES = (ENFORCEMENT_DEFAULT, ENFORCEMENT_ALWAYS, ENFORCEMENT_NEVER)

# --------------------------------------------------------------------------- #
# Health probing (cilium-health analog): the node's health prober sources
# probes from this link-local address, mapped to the reserved health
# identity in the ipcache at engine startup.
# --------------------------------------------------------------------------- #
HEALTH_PROBE_IP = "169.254.254.254"
ICMP_ECHO_REQUEST = 8

# Engine health states (supervised degradation — runtime/engine.health()):
# OK = serving the current compiled snapshot; DEGRADED = regeneration
# failing, serving the last-good snapshot (still semantically current);
# STALE = regeneration failing with committed policy changes pending.
HEALTH_OK = "OK"
HEALTH_DEGRADED = "DEGRADED"
HEALTH_STALE = "STALE"
HEALTH_STATES = (HEALTH_OK, HEALTH_DEGRADED, HEALTH_STALE)
# health() detail key: a registered bounded structure is past its warn
# pressure fraction (observe/pressure.py resource ledger, ISSUE 13)
RESOURCE_PRESSURE = "RESOURCE_PRESSURE"
# Clustermesh staleness detail (runtime/clustermesh.status()): the store
# has been unreachable past the staleness budget — remote state still
# serves last-good (never fail closed on established remote flows), but
# the view may be behind the mesh; folds Engine.health() to DEGRADED.
MESH_STALE = "MESH_STALE"
# CT-archive staleness detail (ISSUE 19): the ct-snapshot controller's
# newest archive is older than checkpoint_max_age_s — the salvage floor a
# device-loss re-mesh would fall back to no longer reflects recent flows;
# folds Engine.health() to DEGRADED until a snapshot lands.
CHECKPOINT_STALE = "CHECKPOINT_STALE"
# Device-loss detail (ISSUE 19): an accelerator in the configured mesh is
# latched dead (runtime/datapath.device_health) — serving continues on the
# survivor mesh, but the cluster is one fault from losing redundancy.
DEVICE_LOST = "DEVICE_LOST"

# --------------------------------------------------------------------------- #
# L7-lite (config 4): tokenized HTTP method/path-prefix matching
# --------------------------------------------------------------------------- #
HTTP_METHODS = (
    "GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS", "PATCH", "TRACE", "CONNECT",
)
HTTP_METHOD_IDS = {m: i for i, m in enumerate(HTTP_METHODS)}
HTTP_METHOD_ANY = 255
L7_PATH_MAXLEN = 64
