"""Port equivalence classes (the compile-time half of the policymap key).

All L4 port ranges appearing in any MapState entry partition the 0..65535
space per proto family into equivalence classes: two ports in the same class
are covered by exactly the same set of entries, so the dense verdict tensor
needs one column per class, not per port. Classes are globally numbered
across families (each family owns a contiguous class range), giving the
device a single ``class = table[family, dport]`` gather.

This is the classic bitmap/equivalence-class trick from packet-classification
literature, applied at compile time so the TPU lookup is O(1) gathers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from cilium_tpu.utils import constants as C


@dataclass(frozen=True)
class PortClassTable:
    table: np.ndarray          # [N_PROTO_FAMILIES, 65536] int32 → global class
    n_classes: int
    # per family: list of (lo, hi) covered by each local class, for inspection
    family_class_ranges: Tuple[Tuple[Tuple[int, int], ...], ...]

    def classes_for_range(self, family: int, lo: int, hi: int) -> np.ndarray:
        """Global class ids intersecting [lo, hi] in ``family`` (sorted)."""
        return np.unique(self.table[family, lo:hi + 1])


def build_port_classes(
    ranges_by_family: Dict[int, Iterable[Tuple[int, int]]],
) -> PortClassTable:
    """``ranges_by_family[family]`` = all (lo, hi) port ranges any entry uses
    in that family (wildcard (0, 65535) need not be included — it maps to
    every class anyway)."""
    table = np.zeros((C.N_PROTO_FAMILIES, 65536), dtype=np.int32)
    next_class = 0
    all_ranges: List[Tuple[Tuple[int, int], ...]] = []
    for family in range(C.N_PROTO_FAMILIES):
        boundaries = {0, 65536}
        for lo, hi in ranges_by_family.get(family, ()):  # inclusive ranges
            boundaries.add(lo)
            boundaries.add(hi + 1)
        cuts = sorted(b for b in boundaries if 0 <= b <= 65536)
        fam_ranges: List[Tuple[int, int]] = []
        for lo, hi_excl in zip(cuts[:-1], cuts[1:]):
            table[family, lo:hi_excl] = next_class
            fam_ranges.append((lo, hi_excl - 1))
            next_class += 1
        all_ranges.append(tuple(fam_ranges))
    return PortClassTable(table=table, n_classes=next_class,
                          family_class_ranges=tuple(all_ranges))
