"""MapState → dense verdict tensors: the precedence ladder resolved at
compile time.

The datapath ladder (deny-wins → most-specific allow → default) is evaluated
ONCE per (id_class, port_class) cell here, so the device lookup is two
gathers (class maps) + one gather (cell) instead of a wildcard-ladder walk —
the TPU-first replacement for per-packet policymap probing
(upstream: ``bpf/lib/policy.h`` policy_can_access).

Cell encoding (uint16): low 2 bits = decision (MISS/ALLOW/DENY/REDIRECT),
high 14 bits = L7 set id for REDIRECT cells.

Equivalence with the sparse ladder is by construction:
- deny entries are OR-accumulated into a deny mask (deny wins regardless of
  rank, mirroring MapState.lookup);
- allow entries compete per cell on the scalar rank (see
  policy.mapstate.rank_scalar — order-isomorphic to the ladder's tie-break
  for same-cell candidates);
and is additionally test-enforced cell-by-cell against MapState.lookup.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterable, List, Tuple

import numpy as np

from cilium_tpu.compile.idclass import IdentityClasses
from cilium_tpu.compile.l7 import L7SetInterner
from cilium_tpu.compile.portclass import PortClassTable
from cilium_tpu.policy.mapstate import MapState, rank_scalar
from cilium_tpu.policy.repository import EndpointPolicy
from cilium_tpu.utils import constants as C


@dataclass(frozen=True)
class PolicyImage:
    """Dense verdict state for all endpoints of one snapshot."""
    verdict: np.ndarray    # [n_eps, 2, n_id_classes, n_port_classes] uint16
    enforced: np.ndarray   # [n_eps, 2] bool

    @property
    def nbytes(self) -> int:
        return self.verdict.nbytes + self.enforced.nbytes


class OverlayImage:
    """A delta-emitted policy image: an immutable shared ``base`` verdict
    array plus a frozen ``{(slot, dir, id_class): row_values}`` overlay.

    This is what makes sub-ms incremental updates possible on the host: the
    incremental compiler emits one of these per delta cycle instead of
    copying the whole dense image (O(200MB) for a 50k-rule world — the cost
    that put BENCH_r05's rule add at ~620ms). The serving path never
    touches ``.verdict``: the datapath scatter-applies the patch's sparse
    (rows, values) delta straight onto the device-resident image. Dense
    access (FakeDatapath placement, tests, a full re-place after a
    geometry fallback) materializes lazily — base copy + overlay rows —
    and caches, so each emitted snapshot still reads as its own immutable
    full array (the COW/revision-fencing contract of
    ``test_emitted_snapshots_stay_frozen`` holds: the base is never
    mutated in place, and overlay row arrays are frozen at emission)."""

    __slots__ = ("_base", "_rows", "enforced", "_dense", "_lock")

    def __init__(self, base: np.ndarray,
                 rows: "dict[Tuple[int, int, int], np.ndarray]",
                 enforced: np.ndarray):
        self._base = base
        self._rows = rows              # frozen at construction (caller copies)
        self.enforced = enforced
        self._dense = None
        self._lock = threading.Lock()

    @property
    def verdict(self) -> np.ndarray:
        dense = self._dense
        if dense is None:
            with self._lock:
                dense = self._dense
                if dense is None:
                    dense = self._base.copy()
                    for (slot, d, row), vals in self._rows.items():
                        dense[slot, d, row, :] = vals
                    self._dense = dense
        return dense

    @property
    def overlay_rows(self) -> int:
        return len(self._rows)

    @property
    def nbytes(self) -> int:
        # logical image size (what a dense materialization would occupy) —
        # computed WITHOUT materializing, so the policy_image_bytes gauge
        # on the delta path stays O(1)
        return self._base.nbytes + self.enforced.nbytes


def build_policy_image(
    policies: List[EndpointPolicy],      # index == ep slot
    id_classes: IdentityClasses,
    port_classes: PortClassTable,
    l7: L7SetInterner,
) -> PolicyImage:
    n_eps = len(policies)
    n_rows = id_classes.n_classes
    n_cols = port_classes.n_classes
    verdict = np.zeros((n_eps, 2, n_rows, n_cols), dtype=np.uint16)
    enforced = np.zeros((n_eps, 2), dtype=bool)

    for slot, pol in enumerate(policies):
        for direction, dirpol in ((C.DIR_EGRESS, pol.egress),
                                  (C.DIR_INGRESS, pol.ingress)):
            enforced[slot, direction] = dirpol.enforced
            if not dirpol.enforced:
                # Unenforced direction = allow-all: the oracle skips the
                # ladder entirely (even denies), so the plane stays all-MISS
                # and the kernel's ~enforced MISS path allows. Compiling the
                # entries anyway would wrongly apply DENY/REDIRECT cells.
                continue
            verdict[slot, direction] = _build_plane(
                dirpol.mapstate, id_classes, port_classes, l7,
                n_rows, n_cols)
    return PolicyImage(verdict=verdict, enforced=enforced)


def _build_plane(ms: MapState, id_classes: IdentityClasses,
                 port_classes: PortClassTable, l7: L7SetInterner,
                 n_rows: int, n_cols: int) -> np.ndarray:
    deny = np.zeros((n_rows, n_cols), dtype=bool)
    best_rank = np.full((n_rows, n_cols), -1, dtype=np.int64)
    allow_val = np.zeros((n_rows, n_cols), dtype=np.uint16)

    for key, entry in ms.items():
        # rows
        if key.identity == C.IDENTITY_ANY:
            rows = None                                   # all rows
        else:
            idx = id_classes.index_of.get(key.identity)
            if idx is None:
                continue                                  # identity not in snapshot
            rows = np.asarray([id_classes.class_of[idx]])
        # cols
        if key.proto == C.PROTO_ANY:
            cols = None                                   # all columns
        else:
            fam = C.proto_family(key.proto)
            if fam == C.PROTO_FAMILY_OTHER:
                # The dense image can only represent proto-exact semantics
                # for protocols with their own family; a proto-specific entry
                # for e.g. GRE would silently conflate with every other
                # OTHER-family protocol. The rule parser never emits these;
                # reject rather than mis-compile.
                raise ValueError(
                    f"cannot compile proto-specific entry for protocol "
                    f"{key.proto} (no dedicated proto family)")
            cols = port_classes.classes_for_range(fam, key.port_lo, key.port_hi)
            if cols.size == 0:
                continue

        if entry.deny:
            _write_mask(deny, rows, cols, True)
            continue

        if entry.l7_rules is not None:
            cell = C.verdict_cell(C.VERDICT_REDIRECT, l7.intern(entry.l7_rules))
        else:
            cell = C.verdict_cell(C.VERDICT_ALLOW)
        rank = rank_scalar(key)
        _write_ranked(best_rank, allow_val, rows, cols, rank, cell)

    out = allow_val.copy()
    out[best_rank < 0] = C.VERDICT_MISS
    out[deny] = C.verdict_cell(C.VERDICT_DENY)
    return out


def _write_mask(arr: np.ndarray, rows, cols, value) -> None:
    if rows is None and cols is None:
        arr[:, :] = value
    elif rows is None:
        arr[:, cols] = value
    elif cols is None:
        arr[rows, :] = value
    else:
        arr[np.ix_(rows, cols)] = value


def _write_ranked(best_rank: np.ndarray, val: np.ndarray, rows, cols,
                  rank: int, cell: int) -> None:
    """best-rank-wins scatter. Ranks of distinct keys covering the same cell
    are distinct (see rank_scalar), so no equal-rank conflicts exist."""
    if rows is None:
        rows = np.arange(best_rank.shape[0])
    if cols is None:
        cols = np.arange(best_rank.shape[1])
    ix = np.ix_(rows, cols)
    sub = best_rank[ix]
    m = rank > sub
    if m.any():
        sub[m] = rank
        best_rank[ix] = sub
        vsub = val[ix]
        vsub[m] = cell
        val[ix] = vsub
