"""ipcache → stride-8 multibit trie tensors (the LPM "map").

Replaces the kernel's LPM_TRIE map (upstream ``pkg/maps/ipcache``; datapath
lookup in ``bpf/lib/eps.h``) with gather-chain tables: one trie per address
family (mirroring upstream's separate v4/v6 maps), stride 8 bits, so an IPv4
lookup is 4 dependent gathers and IPv6 is 16 — cost independent of prefix
count (SURVEY.md §5: "LPM over 100k prefixes as multi-level stride tables").

Node layout: ``nodes[n, 256, 3] int32`` —
  ``nodes[x, b, 0]`` = child node index, or -1 (no child);
  ``nodes[x, b, 1]`` = identity *index* decided at this byte, or -1 (inherit
  the best match seen so far along the path);
  ``nodes[x, b, 2]`` = packed match provenance ``(prefix_slot << 8) | plen``
  for the prefix that decided this value, or -1. Prefix slots enumerate the
  snapshot's canonical prefixes in sorted order (``LPMTables.prefixes``), so
  a verdict can name the exact ipcache entry that won the walk — the
  match-provenance column the observer/flowlog surfaces (ISSUE 11).
A sentinel "dead" node of all -1 lets the fixed-depth device loop run to full
depth without data-dependent control flow: after a path ends, the gather
chain idles in the dead node. Misses resolve to ``default_index``
(reserved:world) with provenance -1, matching the datapath's WORLD_ID
fallback.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from cilium_tpu.utils.ip import parse_prefix

V4_LEVELS = 4     # bytes 12..15 of the v4-mapped address
V6_LEVELS = 16

#: lpm_prefix packing: low 8 bits = canonical prefix length (0..128), the
#: rest = prefix slot. One shared constant so the kernels, the oracle and
#: the observer un-pack identically.
PFX_LEN_BITS = 8
PFX_LEN_MASK = (1 << PFX_LEN_BITS) - 1


def pack_pfx(slot: int, plen: int) -> int:
    return (slot << PFX_LEN_BITS) | (plen & PFX_LEN_MASK)


def unpack_pfx(packed: int) -> Tuple[int, int]:
    """packed lpm_prefix → (slot, plen); (-1, -1) for the miss sentinel."""
    if packed < 0:
        return -1, -1
    return packed >> PFX_LEN_BITS, packed & PFX_LEN_MASK


@dataclass(frozen=True)
class LPMTables:
    """Host-built trie tensors for one snapshot."""
    v4_nodes: np.ndarray   # [n4, 256, 3] int32
    v6_nodes: np.ndarray   # [n6, 256, 3] int32
    default_index: int     # identity index for LPM miss (world)
    # slot → canonical prefix string (sorted enumeration of the compiled
    # ipcache); the inverse map resolves oracle/observer lookups to the
    # same slot ids the device trie carries in its provenance plane
    prefixes: Tuple[str, ...] = ()
    pfx_slot_of: Dict[str, int] = field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        return self.v4_nodes.nbytes + self.v6_nodes.nbytes

    def describe(self, packed: int) -> Dict:
        """Un-pack one lpm_prefix provenance value for display."""
        slot, plen = unpack_pfx(int(packed))
        if slot < 0 or slot >= len(self.prefixes):
            return {"slot": -1, "prefix": None, "plen": -1}
        return {"slot": slot, "prefix": self.prefixes[slot], "plen": plen}


class _TrieBuilder:
    def __init__(self):
        # node 0 is the root; each node is {byte: child_idx} + per-byte value
        self.children: List[Dict[int, int]] = [{}]
        # values[node][b] = (plen_bits, identity_index, packed_provenance)
        self.values: List[Dict[int, Tuple[int, int, int]]] = [{}]

    def _new_node(self) -> int:
        self.children.append({})
        self.values.append({})
        return len(self.children) - 1

    def insert(self, addr_bytes: bytes, plen_bits: int, value: int,
               meta: int = -1) -> None:
        """Insert a prefix of ``plen_bits`` (multiple-of-8 boundary handled by
        expansion: a /12 covers 2^(16-12)=16 byte-values at level 2).
        ``meta`` is the packed provenance stored alongside the value — the
        winner of a cell carries both, so value and provenance can never
        name different prefixes."""
        node = 0
        full_bytes, rem_bits = divmod(plen_bits, 8)
        for level in range(full_bytes):
            b = addr_bytes[level]
            if level == full_bytes - 1 and rem_bits == 0:
                old = self.values[node].get(b)
                if old is None or old[0] <= plen_bits:
                    self.values[node][b] = (plen_bits, value, meta)
                return
            child = self.children[node].get(b)
            if child is None:
                child = self._new_node()
                self.children[node][b] = child
            node = child
        # partial byte: expand the remaining bits over the byte range
        b0 = addr_bytes[full_bytes] & (0xFF << (8 - rem_bits)) if rem_bits else 0
        span = 1 << (8 - rem_bits) if rem_bits else 256
        for b in range(b0, b0 + span):
            old = self.values[node].get(b)
            if old is None or old[0] <= plen_bits:
                self.values[node][b] = (plen_bits, value, meta)

    def to_array(self) -> np.ndarray:
        n = len(self.children)
        arr = np.full((n + 1, 256, 3), -1, dtype=np.int32)  # +1 dead node
        for idx in range(n):
            for b, child in self.children[idx].items():
                arr[idx, b, 0] = child
            for b, (_plen, value, meta) in self.values[idx].items():
                arr[idx, b, 1] = value
                arr[idx, b, 2] = meta
        return arr

    @property
    def dead_node(self) -> int:
        return len(self.children)


def build_lpm(ipcache_entries: Dict[str, int],
              identity_index: Dict[int, int],
              default_index: int) -> LPMTables:
    """Build trie tensors from an ipcache snapshot.

    ``identity_index`` maps identity id → dense index (the LPM leaf payload);
    entries referencing unknown identities raise (the compiler must be handed
    a consistent snapshot). Prefix slots are assigned in sorted canonical
    order — deterministic for any snapshot content, independent of the
    ipcache dict's insertion history.
    """
    b4, b6 = _TrieBuilder(), _TrieBuilder()
    prefixes = tuple(sorted(ipcache_entries))
    pfx_slot_of = {p: s for s, p in enumerate(prefixes)}
    for prefix in prefixes:
        ident = ipcache_entries[prefix]
        addr16, plen, is_v6 = parse_prefix(prefix)
        idx = identity_index[ident]
        meta = pack_pfx(pfx_slot_of[prefix], plen)
        if is_v6:
            b6.insert(addr16, plen, idx, meta)
        else:
            # v4: trie over the last 4 bytes; /96+p → p bits here
            b4.insert(addr16[12:], plen - 96, idx, meta)
    return LPMTables(v4_nodes=b4.to_array(), v6_nodes=b6.to_array(),
                     default_index=default_index,
                     prefixes=prefixes, pfx_slot_of=pfx_slot_of)


def lpm_lookup_host(tables: LPMTables, addr16: bytes, is_v6: bool) -> int:
    """Host-side reference walk of the trie tensors (for tests; the jnp
    kernel in kernels/lpm.py must agree with this AND with
    model.ipcache.lpm_lookup)."""
    return lpm_lookup_host_prov(tables, addr16, is_v6)[0]


def lpm_lookup_host_prov(tables: LPMTables, addr16: bytes,
                         is_v6: bool) -> Tuple[int, int]:
    """Reference walk returning (identity index, packed lpm_prefix
    provenance) — the host mirror of kernels/lpm.lpm_walk_prov_core."""
    nodes = tables.v6_nodes if is_v6 else tables.v4_nodes
    data = addr16 if is_v6 else addr16[12:]
    levels = V6_LEVELS if is_v6 else V4_LEVELS
    node = 0
    dead = nodes.shape[0] - 1
    best = tables.default_index
    best_meta = -1
    for level in range(levels):
        b = data[level]
        child, value, meta = nodes[node, b]
        if value >= 0:
            best = int(value)
            best_meta = int(meta)
        node = int(child) if child >= 0 else dead
    return best, best_meta
