"""Service load-balancer tensors — the lbmap analog (SURVEY.md §2
"Services/LB": upstream ``pkg/service`` programs ``pkg/maps/lbmap``; the
datapath consumes it in ``bpf/lib/lb.h`` — lb4_lookup_service →
lb4_select_backend → DNAT, reverse NAT via the revnat map).

TPU-native layout:

- **Frontend table**: open-addressed hash table over (addr[4 words], port,
  proto) → frontend index, probed exactly like the conntrack table (same
  murmur mix, fixed probe depth). Built host-side; capacity grows until every
  key fits inside the probe window, so device lookups are bounded.
- **Maglev tables**: one row per service, ``[n_services, M]`` (M prime) of
  global backend indices — consistent hashing so backend churn re-steers
  ~1/B of flows (upstream: pkg/loadbalancer Maglev). Weighted backends take
  proportionally many table slots.
- **Backend arrays**: ``be_addr [B,4]``, ``be_port [B]``.
- **Rev-NAT arrays**: per frontend VIP/port, gathered on the reply path to
  un-DNAT (upstream: lb4_rev_nat via the CT entry's rev_nat_index).

Backend selection is **stateless-deterministic**: hash of the un-translated
5-tuple mod M. The same flow always picks the same backend while the backend
set is unchanged; on backend change Maglev bounds re-steering. (Upstream
additionally pins a flow's backend in a CT_SERVICE entry; the stateless form
is the TPU-friendly equivalent and is what the oracle specifies.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.kernels.hashing import hash_words_np
from cilium_tpu.model.services import Backend, Frontend, Service
from cilium_tpu.utils.ip import addr_to_words, parse_addr

FE_KEY_WORDS = 6          # addr[4], port, proto
LB_PROBE_DEPTH = 8
MAGLEV_M_DEFAULT = 251    # prime; production-sized tables use 16381


@dataclass(frozen=True)
class LBConfig:
    maglev_m: int = MAGLEV_M_DEFAULT
    probe_depth: int = LB_PROBE_DEPTH


@dataclass(frozen=True)
class LBTables:
    """Compiled LB state. Device-facing arrays + host metadata.

    Rev-NAT ids are STABLE across snapshots (allocated by the
    ServiceRegistry, never reused): CT entries store ``rnat_id + 1`` and the
    reply path resolves it against ``rnat_addr/rnat_port/rnat_valid``, which
    are indexed by id — a service deleted between snapshots leaves its row
    invalid, so stale CT entries fail closed (no rewrite) instead of
    rewriting to another service's VIP."""
    tab_keys: np.ndarray        # [cap, 6] uint32 — 0-key = empty
    tab_val: np.ndarray         # [cap] int32 frontend idx (-1 empty)
    fe_service: np.ndarray      # [F] int32 → maglev row
    fe_rnat_id: np.ndarray      # [F] int32 stable rev-NAT id
    rnat_addr: np.ndarray       # [R, 4] uint32 (the VIP), indexed by id
    rnat_port: np.ndarray       # [R] int32
    rnat_valid: np.ndarray      # [R] bool
    maglev: np.ndarray          # [S, M] int32 global backend idx (-1 = none)
    be_addr: np.ndarray         # [B, 4] uint32
    be_port: np.ndarray         # [B] int32
    probe_depth: int
    # host-side metadata (CLI / oracle / trace)
    frontends: Tuple[Frontend, ...]
    fe_names: Tuple[str, ...]   # "namespace/name" per frontend
    backends: Tuple[Backend, ...]

    @property
    def n_frontends(self) -> int:
        return len(self.frontends)

    def tensors(self) -> Dict[str, np.ndarray]:
        return {
            "lb_tab_keys": self.tab_keys,
            "lb_tab_val": self.tab_val,
            "lb_fe_service": self.fe_service,
            "lb_fe_rnat_id": self.fe_rnat_id,
            "lb_rnat_addr": self.rnat_addr,
            "lb_rnat_port": self.rnat_port,
            "lb_rnat_valid": self.rnat_valid,
            "lb_maglev": self.maglev,
            "lb_be_addr": self.be_addr,
            "lb_be_port": self.be_port,
        }


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n ** 0.5) + 1):
        if n % p == 0:
            return False
    return True


def _fe_key_words(addr16: bytes, port: int, proto: int) -> np.ndarray:
    w = addr_to_words(addr16)
    return np.array([w[0], w[1], w[2], w[3], port, proto], dtype=np.uint32)


def _str_hash_words(s: str) -> np.ndarray:
    data = s.encode()
    data += b"\x00" * (-len(data) % 4)
    return np.frombuffer(data, dtype="<u4").astype(np.uint32)


def maglev_table(backends: Sequence[Backend], m: int) -> np.ndarray:
    """Standard Maglev population (the upstream pkg/loadbalancer algorithm
    shape): each backend gets a permutation of [0, M) from (offset, skip)
    derived from its name hash; backends take turns claiming their next
    unclaimed slot, weighted backends take ``weight`` consecutive turns."""
    if not _is_prime(m):
        raise ValueError(f"maglev M must be prime, got {m}")
    n = len(backends)
    if n == 0:
        return np.full((m,), -1, dtype=np.int32)
    offsets = np.empty(n, dtype=np.int64)
    skips = np.empty(n, dtype=np.int64)
    for i, b in enumerate(backends):
        name = f"{b.addr}:{b.port}"
        h1 = int(hash_words_np(_str_hash_words(name + "#o"))[()])
        h2 = int(hash_words_np(_str_hash_words(name + "#s"))[()])
        offsets[i] = h1 % m
        skips[i] = h2 % (m - 1) + 1
    table = np.full((m,), -1, dtype=np.int32)
    next_idx = np.zeros(n, dtype=np.int64)
    filled = 0
    while filled < m:
        for i, b in enumerate(backends):
            for _ in range(b.weight):
                # claim the backend's next unclaimed permutation slot
                while True:
                    c = (offsets[i] + next_idx[i] * skips[i]) % m
                    next_idx[i] += 1
                    if table[c] < 0:
                        table[c] = i
                        filled += 1
                        break
                if filled == m:
                    return table
    return table


def build_lb(registry_or_services,
             cfg: Optional[LBConfig] = None) -> LBTables:
    """Compile LB state. Deterministic given the service set
    (services/frontends iterated in sorted registry order).

    Accepts a ServiceRegistry (preferred: its stable rev-NAT id allocator is
    used) or a plain Service sequence (ids fall back to positional — only
    safe when the service set never changes, e.g. one-shot tests)."""
    cfg = cfg or LBConfig()
    if hasattr(registry_or_services, "all"):
        services: Sequence[Service] = registry_or_services.all()
        rnat_id_of = registry_or_services.rnat_id
    else:
        services = registry_or_services
        _pos = {}
        rnat_id_of = lambda fe: _pos.setdefault(  # noqa: E731
            (fe.addr, fe.port, fe.proto), len(_pos))
    frontends: List[Frontend] = []
    fe_names: List[str] = []
    fe_service: List[int] = []
    fe_rnat_ids: List[int] = []
    maglev_rows: List[np.ndarray] = []
    all_backends: List[Backend] = []

    for svc in services:
        if not svc.frontends:
            continue
        base = len(all_backends)
        local = list(svc.lb_backends)
        all_backends.extend(local)
        row = maglev_table(local, cfg.maglev_m)
        row = np.where(row >= 0, row + base, -1).astype(np.int32)
        srow = len(maglev_rows)
        maglev_rows.append(row)
        for fe in svc.frontends:
            frontends.append(fe)
            fe_names.append(f"{svc.namespace}/{svc.name}")
            fe_service.append(srow)
            fe_rnat_ids.append(rnat_id_of(fe))

    F = len(frontends)
    B = len(all_backends)
    S = len(maglev_rows)
    R = max(fe_rnat_ids) + 1 if fe_rnat_ids else 1
    rnat_addr = np.zeros((R, 4), dtype=np.uint32)
    rnat_port = np.zeros((R,), dtype=np.int32)
    rnat_valid = np.zeros((R,), dtype=bool)
    fe_keys = np.zeros((max(F, 1), FE_KEY_WORDS), dtype=np.uint32)
    seen_keys = {}
    for i, fe in enumerate(frontends):
        addr16, _v6 = parse_addr(fe.addr)
        fe_keys[i] = _fe_key_words(addr16, fe.port, fe.proto)
        k = (addr16, fe.port, fe.proto)
        if k in seen_keys:
            raise ValueError(
                f"duplicate service frontend {fe.addr}:{fe.port}/{fe.proto}: "
                f"declared by both {fe_names[seen_keys[k]]} and {fe_names[i]}")
        seen_keys[k] = i
        rid = fe_rnat_ids[i]
        rnat_addr[rid] = fe_keys[i, :4]
        rnat_port[rid] = fe.port
        rnat_valid[rid] = True

    be_addr = np.zeros((max(B, 1), 4), dtype=np.uint32)
    be_port = np.zeros((max(B, 1),), dtype=np.int32)
    for i, b in enumerate(all_backends):
        addr16, _v6 = parse_addr(b.addr)
        be_addr[i] = np.array(addr_to_words(addr16), dtype=np.uint32)
        be_port[i] = b.port

    maglev = (np.stack(maglev_rows) if S
              else np.full((1, cfg.maglev_m), -1, dtype=np.int32))

    # open-addressed frontend table; grow until every key fits in the window
    cap = 8
    while cap < 2 * max(F, 1):
        cap *= 2
    while True:
        tab_keys = np.zeros((cap, FE_KEY_WORDS), dtype=np.uint32)
        tab_val = np.full((cap,), -1, dtype=np.int32)
        ok = True
        for i in range(F):
            base_h = int(hash_words_np(fe_keys[i])[()]) & (cap - 1)
            for d in range(cfg.probe_depth):
                s = (base_h + d) & (cap - 1)
                if tab_val[s] < 0:
                    tab_keys[s] = fe_keys[i]
                    tab_val[s] = i
                    break
            else:
                ok = False
                break
        if ok:
            break
        cap *= 2

    return LBTables(
        tab_keys=tab_keys, tab_val=tab_val,
        fe_service=np.asarray(fe_service, dtype=np.int32)
        if F else np.zeros((1,), dtype=np.int32),
        fe_rnat_id=np.asarray(fe_rnat_ids, dtype=np.int32)
        if F else np.zeros((1,), dtype=np.int32),
        rnat_addr=rnat_addr, rnat_port=rnat_port, rnat_valid=rnat_valid,
        maglev=maglev, be_addr=be_addr, be_port=be_port,
        probe_depth=cfg.probe_depth,
        frontends=tuple(frontends), fe_names=tuple(fe_names),
        backends=tuple(all_backends),
    )


# --------------------------------------------------------------------------- #
# Host mirrors (one definition of the semantics — the jnp executor in
# kernels/lb.py must agree bit-for-bit; test-enforced)
# --------------------------------------------------------------------------- #
def lb_select_words_np(batch) -> np.ndarray:
    """[N, 10] uint32 backend-selection words: the forward CT key with the
    direction bits masked off. Selection only ever runs on un-translated
    forward packets (dst = VIP) — replies carry the client address as dst and
    never match a frontend — so this just has to be deterministic per flow."""
    src, dst = batch["src"], batch["dst"]
    return np.stack([
        src[:, 0], src[:, 1], src[:, 2], src[:, 3],
        dst[:, 0], dst[:, 1], dst[:, 2], dst[:, 3],
        (batch["sport"].astype(np.uint32) << np.uint32(16))
        | batch["dport"].astype(np.uint32),
        batch["proto"].astype(np.uint32) << np.uint32(8),
    ], axis=-1).astype(np.uint32)


def lb_lookup_np(lb: LBTables, batch) -> np.ndarray:
    """Frontend index per packet (-1 = no service). Mirrors kernels/lb.py."""
    n = batch["dport"].shape[0]
    keys = np.stack([
        batch["dst"][:, 0], batch["dst"][:, 1],
        batch["dst"][:, 2], batch["dst"][:, 3],
        batch["dport"].astype(np.uint32), batch["proto"].astype(np.uint32),
    ], axis=-1).astype(np.uint32)
    cap = lb.tab_keys.shape[0]
    base = hash_words_np(keys).astype(np.int64) & (cap - 1)
    found = np.full((n,), -1, dtype=np.int32)
    for d in range(lb.probe_depth):
        s = (base + d) & (cap - 1)
        eq = (lb.tab_keys[s] == keys).all(axis=-1) & (lb.tab_val[s] >= 0)
        found = np.where((found < 0) & eq, lb.tab_val[s], found)
    return found


def lb_translate_np(lb: LBTables, batch):
    """Host mirror of the kernel's LB step → (new_dst, new_dport, rev_nat,
    no_backend, fe_idx). rev_nat is the frontend's stable rev-NAT id + 1
    (0 = untranslated)."""
    fe_idx = lb_lookup_np(lb, batch)
    hit = (fe_idx >= 0) & np.asarray(batch["valid"])
    safe_fe = np.where(hit, fe_idx, 0)
    h = hash_words_np(lb_select_words_np(batch)).astype(np.int64)
    m = lb.maglev.shape[1]
    be = lb.maglev[lb.fe_service[safe_fe], h % m]
    no_backend = hit & (be < 0)
    do = hit & (be >= 0)
    safe_be = np.where(do, be, 0)
    new_dst = np.where(do[:, None], lb.be_addr[safe_be], batch["dst"])
    new_dport = np.where(do, lb.be_port[safe_be], batch["dport"])
    rev_nat = np.where(do, lb.fe_rnat_id[safe_fe] + 1, 0).astype(np.int32)
    return new_dst, new_dport, rev_nat, no_backend, fe_idx
