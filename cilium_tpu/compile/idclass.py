"""Identity equivalence classes (the compile-time half of the identity axis).

Two identities whose concrete-keyed MapState entries are identical across
every (endpoint, direction) in the snapshot always receive identical verdict
rows, so the dense tensor needs one row per *class*, not per identity. This
is the rule-space compression that keeps a 10k-identity × 50k-rule policy in
HBM (SURVEY.md §2 parallelism table: "policymap tensors sharded by
identity-row" — classes shrink the row space before sharding even starts).

The signature is computed directly from the MapStates being compiled (not
from selectors), so it is correct by construction: same signature ⇒ same
entries ⇒ same row. Identities mentioned by no concrete entry share class 0
(only wildcard-ANY entries apply to them).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from cilium_tpu.policy.mapstate import MapState


@dataclass(frozen=True)
class IdentityClasses:
    identity_ids: np.ndarray    # [n_identities] int64, sorted — index → id
    index_of: Dict[int, int]    # id → identity index
    class_of: np.ndarray        # [n_identities] int32 — identity index → class
    n_classes: int
    # one representative identity id per class (class 0 may have none → -1)
    representative: np.ndarray  # [n_classes] int64


def build_identity_classes(
    identity_ids: Sequence[int],
    mapstates: Iterable[Tuple[int, int, MapState]],
) -> IdentityClasses:
    """``mapstates`` yields (ep_slot, direction, MapState)."""
    ids = np.asarray(sorted(identity_ids), dtype=np.int64)
    index_of = {int(v): i for i, v in enumerate(ids)}

    # signature: frozenset of (ep, dir, key-sans-identity, value-digest)
    sigs: Dict[int, List] = {int(v): [] for v in ids}
    for ep_slot, direction, ms in mapstates:
        for key, entry in ms.items():
            if key.identity == 0:      # ANY entries apply to every row
                continue
            ident = int(key.identity)
            if ident not in sigs:
                # entry for an identity outside the snapshot's identity set
                # (e.g. already released) — no row to write, skip
                continue
            digest = (ep_slot, direction, key.proto, key.port_lo, key.port_hi,
                      entry.deny,
                      tuple(sorted((h.method, h.path)
                                   for h in entry.l7_rules))
                      if entry.l7_rules is not None else None)
            sigs[ident].append(digest)

    class_index: Dict[frozenset, int] = {frozenset(): 0}
    reps: List[int] = [-1]
    class_of = np.zeros(len(ids), dtype=np.int32)
    for i, ident in enumerate(ids):
        sig = frozenset(sigs[int(ident)])
        cls = class_index.get(sig)
        if cls is None:
            cls = len(class_index)
            class_index[sig] = cls
            reps.append(int(ident))
        class_of[i] = cls
    return IdentityClasses(
        identity_ids=ids,
        index_of=index_of,
        class_of=class_of,
        n_classes=len(class_index),
        representative=np.asarray(reps, dtype=np.int64),
    )
