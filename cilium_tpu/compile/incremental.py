"""Incremental tensor updates: rule changes → verdict-cell patches, not
recompiles (SURVEY.md §3.2 hot spot — upstream applies *incremental policymap
diffs* per endpoint; §7 step 3: "diffable, incremental update = index_update
lists, not recompile").

The full compiler (compile/snapshot.build_snapshot) is O(rules × endpoints)
per change: every rule add, DNS tick, or FQDN refresh re-resolves every
endpoint and re-fills the dense image. This module consumes the Repository's
changelog (policy/repository.py changes_since / expand_rule_for — the
producer side that existed since round 2) and patches only what changed:

- per-rule contribution records are kept refcounted per (endpoint, direction,
  MapStateKey); a change touches only its own keys;
- touched keys re-merge (policy/mapstate.merge_contributions) and map to
  verdict rows through the SAME geometry the snapshot was compiled with —
  identity classes and port classes are *extended in place* (class splits
  append a copied row/column) rather than recomputed;
- only affected rows are re-resolved (deny-OR + rank-max over that row's
  keys — the same ladder compile policy_image._build_plane runs per plane);
- everything that cannot be expressed as a patch falls back to a full
  rebuild through explicit GEOMETRY GATES (identity set growth, ipcache/LB
  change, endpoint set change, enforcement-mode change, changelog overflow).

Equivalence contract (test-enforced, tests/test_incremental.py): after any
sequence of add/remove/refresh, the patched snapshot is semantically
identical to a fresh build_snapshot — same verdict decision and same L7 rule
set for every (endpoint, direction, identity, proto, port). Class partitions
may differ (a split identity is never re-merged), which is representation,
not semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from cilium_tpu.compile.ct_layout import CTConfig
from cilium_tpu.compile.idclass import IdentityClasses
from cilium_tpu.compile.l7 import build_l7_tensors
from cilium_tpu.compile.policy_image import PolicyImage
from cilium_tpu.compile.portclass import PortClassTable
from cilium_tpu.compile.snapshot import PolicySnapshot
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.policy.mapstate import (
    MapState, MapStateEntry, MapStateKey, PORT_WILDCARD, merge_contributions,
    rank_scalar,
)
from cilium_tpu.policy.repository import (
    DirectionPolicy, EndpointPolicy, PolicyContext, Repository,
)
from cilium_tpu.utils import constants as C

# (deny, l7_rules, tag): the semantic payload of one contribution; tag only
# feeds derived_from so `policy trace` can still name the rule.
Norm = Tuple[bool, Optional[FrozenSet], str]

_LOCALHOST_TAG = "allow-localhost"
_LOCALHOST_KEY = MapStateKey(C.IDENTITY_HOST, C.PROTO_ANY, *PORT_WILDCARD)


@dataclass
class SnapshotPatch:
    """What the datapath must re-place after an incremental update. Rows are
    (slot, direction, id_class) indices into the NEW snapshot's verdict
    tensor; ``full_tensors`` lists tensors that changed shape or are too
    small to patch (re-upload wholesale).

    ``delta_rows``/``delta_vals`` are the *sparse delta payload*: the same
    rows as ``verdict_rows`` ([K, 3] int32) paired with their recomputed
    cell values ([K, n_port_classes] uint16), emitted whenever the update
    stayed within the delta budget and no geometry changed. A datapath can
    scatter-apply them onto the device-resident image without ever touching
    the host-side dense tensors — the sub-ms live-patch path. When absent
    (geometry growth, budget exceeded), ``full_tensors`` contains
    ``"verdict"`` and placement falls back to a whole-plane upload."""
    base_revision: int
    verdict_rows: List[Tuple[int, int, int]] = field(default_factory=list)
    full_tensors: Set[str] = field(default_factory=set)
    delta_rows: Optional[np.ndarray] = None   # [K, 3] int32
    delta_vals: Optional[np.ndarray] = None   # [K, n_cols] uint16

    @property
    def is_noop(self) -> bool:
        return not self.verdict_rows and not self.full_tensors

    @property
    def is_delta(self) -> bool:
        """True when the verdict change ships as a sparse (rows, values)
        delta a datapath can scatter-apply in place."""
        return (self.delta_rows is not None
                and "verdict" not in self.full_tensors)


@dataclass
class UpdateStats:
    changes: int = 0
    keys_touched: int = 0
    rows_recomputed: int = 0
    id_class_splits: int = 0
    port_class_splits: int = 0
    delta_rows: int = 0                # rows shipped as a sparse delta
    new_identities: int = 0            # appended identity classes (ISSUE 12)
    retired_identities: int = 0        # tombstoned identities (ISSUE 18)
    lpm_rebuilt: bool = False          # ipcache delta → new trie tensors
    fallback: Optional[str] = None     # reason a full rebuild was required


class _PlaneState:
    """Per (endpoint-slot, direction) contribution index."""

    __slots__ = ("key_entries", "by_ident", "mapstate", "copied")

    def __init__(self):
        self.key_entries: Dict[MapStateKey, Dict[Norm, int]] = {}
        self.by_ident: Dict[int, Set[MapStateKey]] = {}
        self.mapstate = MapState()
        self.copied = False            # COW flag for the current update cycle

    def add(self, key: MapStateKey, norm: Norm) -> None:
        c = self.key_entries.setdefault(key, {})
        c[norm] = c.get(norm, 0) + 1
        self.by_ident.setdefault(key.identity, set()).add(key)

    def remove(self, key: MapStateKey, norm: Norm) -> None:
        c = self.key_entries.get(key)
        if c is None or norm not in c:
            raise KeyError(f"unbalanced contribution removal: {key} {norm}")
        c[norm] -= 1
        if c[norm] == 0:
            del c[norm]
        if not c:
            del self.key_entries[key]
            keys = self.by_ident.get(key.identity)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self.by_ident[key.identity]

    def merged(self, key: MapStateKey) -> Optional[MapStateEntry]:
        c = self.key_entries.get(key)
        if not c:
            return None
        return merge_contributions(
            MapStateEntry(deny=deny, l7_rules=l7, derived_from=(tag,))
            for (deny, l7, tag), n in sorted(
                c.items(), key=lambda kv: kv[0][2]) for _ in range(n))


def _norm_contribs(contribs) -> List[Tuple[int, MapStateKey, Norm]]:
    """repo._rule_contributions output → normalized (dir, key, Norm)."""
    out = []
    for direction, key, entry in contribs:
        tag = entry.derived_from[0] if entry.derived_from else ""
        out.append((direction, key,
                    (entry.deny,
                     frozenset(entry.l7_rules)
                     if entry.l7_rules is not None else None,
                     tag)))
    return out


def _endpoint_sig(endpoints: Sequence[Endpoint]):
    return tuple((ep.ep_id, ep.identity_id, ep.enforcement,
                  tuple(sorted(ep.labels.to_strings())))
                 for ep in endpoints)


class IncrementalCompiler:
    """Stateful snapshot producer: seeded from one full build, then patched
    forward through the Repository changelog. Owned by the Engine; every
    emitted snapshot carries copies of the arrays it changed, so previously
    emitted snapshots stay immutable (revision fencing holds)."""

    #: sparse-delta budget: a cycle recomputing more rows than this ships a
    #: full verdict re-upload instead of a scatter delta (the delta's win is
    #: O(rows) transfer; past this point the whole plane is cheaper and the
    #: bookkeeping noise isn't)
    DELTA_BUDGET_ROWS = 1024
    #: overlay rebase budget: the running row overlay (rows changed since
    #: the last dense materialization) is folded into a fresh base — one
    #: O(image) copy — once it grows past this, so per-emission overlay
    #: copies stay O(budget) and the amortized cost of a long churn run is
    #: O(1) copies per update
    REBASE_ROWS = 4096
    #: identity-growth budget: a cycle absorbing more NEW identities than
    #: this (each appends a verdict row per plane and re-expands matching
    #: rules) falls back to a full rebuild — a mass remote-cluster join is
    #: cheaper as one compile than thousands of appends
    IDENT_GROWTH_MAX = 512
    #: identity-retirement budget (ISSUE 18): a cycle tombstoning more
    #: LOCAL identities than this (each zeroes its class row per plane)
    #: falls back — a mass expiry (cache flush, checkpoint restore) is
    #: cheaper as one compile than thousands of row tombstones
    IDENT_RETIRE_MAX = 512

    def __init__(self, repo: Repository, ctx: PolicyContext,
                 endpoints: Sequence[Endpoint], snap: PolicySnapshot,
                 delta_budget_rows: Optional[int] = None,
                 rebase_rows: Optional[int] = None):
        if snap.l7_interner is None:
            raise ValueError("snapshot lacks compile context (l7_interner)")
        if repo.revision != snap.revision:
            raise ValueError(
                f"snapshot revision {snap.revision} is stale (repository at "
                f"{repo.revision}) — seed from a freshly built snapshot")
        self.repo = repo
        self.ctx = ctx
        self.base = snap
        self.delta_budget_rows = (self.DELTA_BUDGET_ROWS
                                  if delta_budget_rows is None
                                  else delta_budget_rows)
        self.rebase_rows = (self.REBASE_ROWS if rebase_rows is None
                            else rebase_rows)
        # the seed reflects everything up to snap.revision: drain the
        # changelog so a large initial rule load cannot leave the window in
        # permanent overflow (changes_since would return None forever)
        repo.prune_changes(snap.revision)
        self.endpoints = list(endpoints)
        self.ep_sig = _endpoint_sig(endpoints)
        self.identity_sig = tuple(i.id for i in ctx.allocator.all())

        n_eps = len(snap.ep_ids)
        # --- working arrays ---
        # The verdict image is held as (immutable base, row overlay): delta
        # cycles write recomputed rows into ``_overlay`` only, so a 1-rule
        # update never copies the dense image (the O(200MB) host copy that
        # put BENCH_r05's rule add at ~620ms). The base array is NEVER
        # mutated in place — geometry growth and rebases replace it with a
        # fresh array — so every emitted snapshot's (base, frozen-overlay)
        # view stays immutable (the COW/revision-fencing contract).
        # enforced/port_table are small and keep the per-cycle COW copy.
        self._base_verdict = snap.image.verdict
        self._overlay: Dict[Tuple[int, int, int], np.ndarray] = {}
        self._enforced = snap.image.enforced
        self._port_table = snap.port_classes.table
        self._n_port_classes = snap.port_classes.n_classes
        # family-range metadata is derived from the port table (an O(65k)
        # scan per family) — cache it across emissions, invalidate only on
        # a port-class split (the delta path's emissions are sub-ms; this
        # scan was most of what was left)
        self._family_ranges = snap.port_classes.family_class_ranges
        self._arrays_owned = False     # True once this cycle copied them

        # --- identity classes (mutable mirrors) ---
        idc = snap.id_classes
        self.identity_ids = idc.identity_ids
        self.index_of = dict(idc.index_of)
        self._class_of = idc.class_of.copy()
        self._n_classes = idc.n_classes
        self._representative = [int(r) for r in idc.representative]
        self._members: Dict[int, Set[int]] = {}
        for i, ident in enumerate(self.identity_ids):
            self._members.setdefault(int(self._class_of[i]), set()).add(
                int(ident))

        self.l7 = snap.l7_interner          # shared, append-only
        self.last_fallback: Optional[str] = None

        # --- contribution index, seeded from the resident rule set ---
        self.planes: Dict[Tuple[int, int], _PlaneState] = {
            (slot, d): _PlaneState()
            for slot in range(n_eps) for d in (C.DIR_EGRESS, C.DIR_INGRESS)}
        self.rule_contribs: Dict[int, Dict] = {}
        self.enforce_counts: Dict[int, List[int]] = {
            slot: [0, 0] for slot in range(n_eps)}   # [egress, ingress]
        for rule in repo.all_rules():
            self._record_rule(rule, apply_counts=True)
        for slot in range(n_eps):
            if self._enforced_value(slot, C.DIR_INGRESS) \
                    and ctx.allow_localhost:
                self.planes[(slot, C.DIR_INGRESS)].add(
                    _LOCALHOST_KEY, (False, None, _LOCALHOST_TAG))
        # seed mapstates from the snapshot's resolved policies (identical to
        # merging the counters; reuse avoids a second merge pass)
        for slot, pol in enumerate(snap.policies):
            self.planes[(slot, C.DIR_EGRESS)].mapstate = pol.egress.mapstate
            self.planes[(slot, C.DIR_INGRESS)].mapstate = pol.ingress.mapstate

    # ------------------------------------------------------------------ #
    # seeding / bookkeeping
    # ------------------------------------------------------------------ #
    def _record_rule(self, rule, apply_counts: bool) -> Dict:
        """Expand ``rule`` against every endpoint and record (and apply to
        the contribution index) its current contributions."""
        rec = {"per_slot": {}, "enforce": {}}
        for slot, ep in enumerate(self.endpoints):
            if not rule.selects(ep.labels):
                continue
            contribs = _norm_contribs(self.repo.expand_rule_for(rule, ep))
            rec["per_slot"][slot] = contribs
            rec["enforce"][slot] = (int(rule.enforces_egress),
                                    int(rule.enforces_ingress))
            for direction, key, norm in contribs:
                self.planes[(slot, direction)].add(key, norm)
            if apply_counts:
                self.enforce_counts[slot][C.DIR_EGRESS] += \
                    int(rule.enforces_egress)
                self.enforce_counts[slot][C.DIR_INGRESS] += \
                    int(rule.enforces_ingress)
        self.rule_contribs[id(rule)] = rec
        return rec

    def _enforced_value(self, slot: int, direction: int) -> bool:
        ep = self.endpoints[slot]
        mode = ep.enforcement or self.ctx.enforcement_mode
        if mode == C.ENFORCEMENT_ALWAYS:
            return True
        if mode == C.ENFORCEMENT_NEVER:
            return False
        return self.enforce_counts[slot][direction] > 0

    # ------------------------------------------------------------------ #
    # the update entry point
    # ------------------------------------------------------------------ #
    def try_update(self, ct_config: Optional[CTConfig] = None,
                   endpoints: Optional[Sequence[Endpoint]] = None
                   ) -> Optional[Tuple[PolicySnapshot, SnapshotPatch,
                                       UpdateStats]]:
        """Patch the snapshot forward to the repository's current revision.
        Returns None when a geometry gate requires a full rebuild (caller
        runs build_snapshot and re-seeds). ``endpoints`` is the CALLER'S
        current endpoint set — the gate compares it against the seeded set
        (passing nothing skips that gate; only safe when the caller knows
        the set is unchanged).

        ISSUE 12: pure identity GROWTH (new identities allocated, none
        removed — the clustermesh remote-influx / CIDR-rule / FQDN-learn
        shape) and ipcache changes no longer gate to a full rebuild: new
        identities append singleton classes (verdict rows recomputed,
        matching resident rules re-contribute their keys), and an ipcache
        delta rebuilds just the LPM trie tensors into the patch.

        ISSUE 18: LOCAL identity RETIREMENT (FQDN TTL expiry, CIDR rule
        removal) rides the delta path too — the retired id is dropped
        from the index, its now-empty class row tombstones to MISS
        through the sparse delta, and the accompanying ipcache delete
        rebuilds the LPM without the prefix. The class axis never
        shrinks (geometry is stable), so growth + retirement in the
        same cycle — the steady-churn FQDN shape — still ships as one
        patch. Non-local removals and over-budget mass expiries still
        fall back."""
        stats = UpdateStats()
        gate = self._gate(endpoints)
        if gate is not None:
            self.last_fallback = gate
            return None
        gate, new_idents, retired = self._identity_delta()
        if gate is not None:
            self.last_fallback = gate
            return None
        # read the revision BEFORE the snapshot: a concurrent upsert
        # between the two leaves the recorded revision behind the content,
        # which only means one redundant rebuild next cycle — never a
        # missed one
        ipcache_rev = self.ctx.ipcache.revision
        ipcache_dirty = ipcache_rev != self.base.ipcache_revision
        rev_now = self.repo.revision
        changes = self.repo.changes_since(self.base.revision)
        if changes is None:
            self.last_fallback = "changelog-overflow"
            return None
        changes = [c for c in changes if c.revision <= rev_now]
        stats.changes = len(changes)
        # proto-specific entries without a dedicated proto family cannot be
        # expressed in the dense image (compile/policy_image raises on them);
        # the rule parser never emits these, but mirror the full compiler's
        # strictness rather than silently mis-compiling
        for ch in changes:
            if ch.kind not in ("add", "refresh"):
                continue
            for blocks in (ch.rule.ingress, ch.rule.ingress_deny,
                           ch.rule.egress, ch.rule.egress_deny):
                for block in blocks:
                    for pr in block.to_ports:
                        for pp in pr.ports:
                            for proto in pp.protocols():
                                if proto != C.PROTO_ANY and C.proto_family(
                                        proto) == C.PROTO_FAMILY_OTHER:
                                    self.last_fallback = "other-proto-family"
                                    return None

        self._cycle_reset()
        dirty: Set[Tuple[int, int, MapStateKey]] = set()
        patch = SnapshotPatch(base_revision=self.base.revision)
        enforce_before = {slot: (self._enforced_value(slot, 0),
                                 self._enforced_value(slot, 1))
                          for slot in range(len(self.endpoints))}

        # identity growth FIRST: the changelog's re-expansions below must
        # find the new identities already indexed, and growth itself only
        # touches rules that predate this cycle's changes
        forced_rows: Set[Tuple[int, int, int]] = set()
        if new_idents:
            forced_rows = self._grow_identities(new_idents, patch, dirty,
                                                stats)
        # retirement SECOND, still before the changelog replay: the dirty
        # re-merge below must find retired ids already un-indexed (their
        # keys skip, mirroring policy_image's unknown-identity skip) — a
        # retired id reaching _split_identity would grow geometry and
        # force a full verdict upload for what is a row tombstone
        if retired:
            forced_rows |= self._retire_identities(retired, stats)
        for ch in changes:
            self._apply_change(ch, dirty)

        # enforced flips (default mode): planes flip between all-MISS and
        # compiled; allow-localhost synthetic key follows ingress enforcement
        enforced_changed = False
        flipped_planes: Set[Tuple[int, int]] = set()
        for slot in range(len(self.endpoints)):
            for d in (C.DIR_EGRESS, C.DIR_INGRESS):
                now_on = self._enforced_value(slot, d)
                if now_on == enforce_before[slot][d]:
                    continue
                enforced_changed = True
                flipped_planes.add((slot, d))
                if d == C.DIR_INGRESS and self.ctx.allow_localhost:
                    norm = (False, None, _LOCALHOST_TAG)
                    plane = self.planes[(slot, d)]
                    if now_on:
                        plane.add(_LOCALHOST_KEY, norm)
                    else:
                        plane.remove(_LOCALHOST_KEY, norm)
                    dirty.add((slot, d, _LOCALHOST_KEY))

        stats.keys_touched = len(dirty)

        # --- re-merge dirty keys into mapstates; collect affected rows ---
        affected_rows: Set[Tuple[int, int, int]] = set(forced_rows)
        whole_planes: Set[Tuple[int, int]] = set(flipped_planes)
        l7_dirty = False
        for slot, d, key in sorted(
                dirty, key=lambda t: (t[0], t[1], t[2])):
            plane = self._cow_plane(slot, d)
            merged = plane.merged(key)
            if merged is None:
                plane.mapstate.delete_entry(key)
            else:
                plane.mapstate.set_entry(key, merged)
                if merged.is_redirect:
                    # intern now: a brand-new set grows the L7 tensors
                    before = len(self.l7.sets)
                    self.l7.intern(frozenset(merged.l7_rules))
                    if len(self.l7.sets) != before:
                        l7_dirty = True
            # geometry: port side first (may add columns), then identity
            if key.proto != C.PROTO_ANY:
                stats.port_class_splits += self._ensure_port_boundaries(
                    key, patch)
            if key.identity == C.IDENTITY_ANY:
                whole_planes.add((slot, d))
            else:
                idx = self.index_of.get(key.identity)
                if idx is None:
                    continue           # identity outside snapshot (mirror
                                       # policy_image._build_plane)
                cls = int(self._class_of[idx])
                if len(self._members[cls]) > 1:
                    cls = self._split_identity(int(key.identity), idx, patch)
                    stats.id_class_splits += 1
                affected_rows.add((slot, d, cls))

        n_rows = self._base_verdict.shape[2]
        for slot, d in whole_planes:
            for r in range(n_rows):
                affected_rows.add((slot, d, r))

        # --- recompute affected rows (deny-OR + rank-max ladder) ---
        for slot, d, row in sorted(affected_rows):
            self._recompute_row(slot, d, row)
            patch.verdict_rows.append((slot, d, row))
        stats.rows_recomputed = len(affected_rows)

        # --- sparse delta payload (the device scatter-apply fast path) ---
        # past the budget a whole-plane upload beats O(rows) scatter noise;
        # geometry growth (splits) already forced "verdict" into
        # full_tensors above
        if len(affected_rows) > self.delta_budget_rows:
            patch.full_tensors.add("verdict")
        if patch.verdict_rows and "verdict" not in patch.full_tensors:
            patch.delta_rows = np.asarray(patch.verdict_rows,
                                          dtype=np.int32)
            patch.delta_vals = np.stack(
                [self._overlay[t] for t in patch.verdict_rows])
            stats.delta_rows = len(patch.verdict_rows)

        if enforced_changed:
            self._own_arrays()
            for slot, d in flipped_planes:
                self._enforced[slot, d] = self._enforced_value(slot, d)
            patch.full_tensors.add("enforced")
        if l7_dirty:
            patch.full_tensors.update(
                ("l7_methods", "l7_path", "l7_path_len", "l7_valid"))

        # --- ipcache delta: rebuild just the LPM trie tensors ------------
        # (the remote-prefix / CIDR / FQDN-learn surface — O(prefixes),
        # no policy re-resolution; the patch re-ships the two node arrays)
        new_lpm = new_ipcache = None
        if ipcache_dirty:
            from cilium_tpu.compile.lpm import build_lpm
            new_ipcache = self.ctx.ipcache.snapshot()
            new_lpm = build_lpm(
                new_ipcache, self.index_of,
                default_index=self.index_of[C.IDENTITY_WORLD])
            patch.full_tensors.update(("lpm_v4", "lpm_v6"))
            stats.lpm_rebuilt = True

        snap = self._emit(rev_now, ct_config, l7_dirty, lpm=new_lpm,
                          ipcache=new_ipcache,
                          ipcache_revision=ipcache_rev if ipcache_dirty
                          else None)
        self.base = snap
        if new_idents or retired:
            self.identity_sig = tuple(
                i.id for i in self.ctx.allocator.all())
        return snap, patch, stats

    # ------------------------------------------------------------------ #
    # gates
    # ------------------------------------------------------------------ #
    def _gate(self, endpoints: Optional[Sequence[Endpoint]]) -> Optional[str]:
        """Hard geometry gates. Identity growth and ipcache changes are no
        longer here — ``try_update`` absorbs them (ISSUE 12)."""
        if endpoints is not None \
                and _endpoint_sig(endpoints) != self.ep_sig:
            return "endpoint-set-changed"
        if self.ctx.services.revision != self.base.services_revision:
            return "services-changed"
        if self.ctx.enforcement_mode != self.base.enforcement_mode:
            return "enforcement-mode-changed"
        if self.ctx.allow_localhost != self.base.allow_localhost:
            return "allow-localhost-changed"
        return None

    def _identity_delta(self) -> Tuple[Optional[str], List, List[int]]:
        """→ (fallback reason, new identities, retired identity ids).

        Growth appends singleton classes. Retirement (ISSUE 18) is
        absorbable only for LOCAL-scope identities (CIDR/FQDN-learned —
        the TTL-churn population): dropping a member never changes the
        surviving members' shared key pattern, so no re-partition is
        needed — a class emptied by its last member tombstones its row
        to MISS and the class axis keeps the (dead, unreachable) slot.
        Non-local removals stay on the full-rebuild path: reserved/
        cluster identities are structural (world/host/endpoint rows the
        whole image is laid out around), not churn. A
        retired id the ipcache still references also falls back: the
        fresh LPM build would reject it, and the inconsistency means the
        owning rule release has not landed yet. Removal + re-add of the
        same id cannot be confused with stability: allocator ids are
        never reused (monotonic counters)."""
        idents = self.ctx.allocator.all()
        cur = tuple(i.id for i in idents)
        if cur == self.identity_sig:
            return None, [], []
        old = set(self.identity_sig)
        removed = old - set(cur)
        if removed:
            if any(not (rid & C.LOCAL_IDENTITY_SCOPE) for rid in removed):
                return "identity-removed", [], []
            if len(removed) > self.IDENT_RETIRE_MAX:
                return "identity-retire-budget", [], []
            if removed & set(self.ctx.ipcache.snapshot().values()):
                return "identity-retired-live-ipcache", [], []
        new = [i for i in idents if i.id not in old]
        if len(new) > self.IDENT_GROWTH_MAX:
            return "identity-growth-budget", [], []
        return None, new, sorted(removed)

    # ------------------------------------------------------------------ #
    # change application
    # ------------------------------------------------------------------ #
    def _apply_change(self, ch, dirty) -> None:
        rid = id(ch.rule)
        old = self.rule_contribs.pop(rid, None)
        if old is not None:
            for slot, contribs in old["per_slot"].items():
                for direction, key, norm in contribs:
                    self.planes[(slot, direction)].remove(key, norm)
                    dirty.add((slot, direction, key))
            for slot, (eg, ing) in old["enforce"].items():
                self.enforce_counts[slot][C.DIR_EGRESS] -= eg
                self.enforce_counts[slot][C.DIR_INGRESS] -= ing
        if ch.kind in ("add", "refresh"):
            rec = self._record_rule(ch.rule, apply_counts=True)
            for slot, contribs in rec["per_slot"].items():
                for direction, key, _norm in contribs:
                    dirty.add((slot, direction, key))

    # ------------------------------------------------------------------ #
    # copy-on-write plumbing (previously emitted snapshots stay frozen)
    # ------------------------------------------------------------------ #
    def _cycle_reset(self) -> None:
        self._arrays_owned = False
        for plane in self.planes.values():
            plane.copied = False

    def _own_arrays(self) -> None:
        """COW for the SMALL working arrays (enforced [n_eps,2], port_table
        [fams,65536]). The verdict image never copies here — delta cycles
        write the row overlay, geometry growth goes through
        :meth:`_materialize_verdict`."""
        if not self._arrays_owned:
            self._enforced = self._enforced.copy()
            self._port_table = self._port_table.copy()
            self._arrays_owned = True

    def _materialize_verdict(self) -> np.ndarray:
        """Fold the row overlay into a FRESH dense verdict array and make it
        the new base (a rebase). Called before geometry growth (column/row
        append needs the full array) and when the overlay outgrows the
        rebase budget. The previous base is left untouched — snapshots
        emitted against it stay frozen."""
        if self._overlay:
            base = self._base_verdict.copy()
            for (slot, d, row), vals in self._overlay.items():
                base[slot, d, row, :] = vals
            self._base_verdict = base
            self._overlay = {}
        return self._base_verdict

    def _cow_plane(self, slot: int, d: int) -> _PlaneState:
        plane = self.planes[(slot, d)]
        if not plane.copied:
            # overlay COW (policy/mapstate._OverlayEntries): the old full
            # dict copy here was O(entries) per touched plane per cycle —
            # ~1.3ms against the 50k-rule world, the dominant term of a
            # warm-geometry rule add. The overlay copy is O(dirty keys);
            # previously emitted snapshots keep the shared base read-only
            # (the frozen-snapshot contract unchanged), and the copy folds
            # back to a flat dict once the accumulated dirty set outgrows
            # the budget — one amortized O(entries) copy per
            # OVERLAY_FOLD_KEYS touched keys instead of one per cycle.
            plane.mapstate = plane.mapstate.overlay_copy()
            plane.copied = True
        return plane

    # ------------------------------------------------------------------ #
    # geometry growth
    # ------------------------------------------------------------------ #
    def _grow_identities(self, new_idents, patch: SnapshotPatch, dirty,
                         stats: UpdateStats) -> Set[Tuple[int, int, int]]:
        """Append one singleton class per NEW identity (ISSUE 12: remote
        label sets → local identities → compiled rows, without a full
        rebuild). The verdict image grows one row per plane per identity,
        resident rules whose selectors now resolve the identities
        re-contribute keys for them (the selector cache updated live on
        allocation; :meth:`Repository.rules_selecting_identities` is the
        cheap prefilter), and every appended row is recomputed by the
        caller — returns the forced (slot, dir, class) row set. Geometry
        growth ⇒ full verdict re-upload, same as a class split."""
        k = len(new_idents)
        v = self._materialize_verdict()
        self._base_verdict = np.concatenate(
            [v, np.zeros(v.shape[:2] + (k, v.shape[3]), dtype=v.dtype)],
            axis=2)
        # index_of is SHARED with previously-emitted snapshots: copy before
        # the first mutation, or an old snapshot would resolve a new
        # identity id into a class row it does not have
        self.index_of = dict(self.index_of)
        ids = np.asarray([i.id for i in new_idents],
                         dtype=self.identity_ids.dtype)
        base_idx = len(self.identity_ids)
        self.identity_ids = np.concatenate([self.identity_ids, ids])
        self._class_of = np.concatenate(
            [self._class_of,
             np.arange(self._n_classes, self._n_classes + k,
                       dtype=self._class_of.dtype)])
        forced: Set[Tuple[int, int, int]] = set()
        for j, ident in enumerate(new_idents):
            self.index_of[int(ident.id)] = base_idx + j
            cls = self._n_classes
            self._n_classes += 1
            self._members[cls] = {int(ident.id)}
            self._representative.append(int(ident.id))
            for slot in range(len(self.endpoints)):
                forced.add((slot, C.DIR_EGRESS, cls))
                forced.add((slot, C.DIR_INGRESS, cls))
        # contributions: only rules whose selectors resolved a new identity
        # can contribute new keys, and those keys differ from the rule's
        # existing ones ONLY in the identity — filter the re-expansion on
        # it and keep the per-rule records balanced for later removal
        new_ids = {int(i.id) for i in new_idents}
        for rule in self.repo.rules_selecting_identities(new_ids):
            rec = self.rule_contribs.get(id(rule))
            if rec is None:
                continue    # added in THIS cycle's changelog: recorded
                            # (with the new identities) by _apply_change
            for slot, ep in enumerate(self.endpoints):
                if slot not in rec["per_slot"]:
                    continue           # rule does not select this endpoint
                fresh = _norm_contribs(self.repo.expand_rule_for(rule, ep))
                adds = [c for c in fresh if c[1].identity in new_ids]
                for direction, key, norm in adds:
                    self.planes[(slot, direction)].add(key, norm)
                    dirty.add((slot, direction, key))
                rec["per_slot"][slot].extend(adds)
        patch.full_tensors.update(("verdict", "id_class_of",
                                   "identity_ids"))
        stats.new_identities = k
        return forced

    def _retire_identities(self, retired: Sequence[int],
                           stats: UpdateStats
                           ) -> Set[Tuple[int, int, int]]:
        """Drop retired LOCAL identities from the class index (ISSUE 18:
        the FQDN TTL-expiry path). The class AXIS is untouched — geometry
        is stable, so the cycle still qualifies for the sparse delta —
        but a class whose last member retired is forced for recompute on
        every plane: with no members left, :meth:`_recompute_row`
        tombstones the row to MISS (the "zeroed policy row"). The dead
        row is unreachable anyway once the accompanying ipcache delete
        rebuilds the LPM without the prefix; zeroing it keeps the device
        image equivalent to what a fresh build would never have
        compiled. ``identity_ids``/``class_of`` keep their dead entries
        host- and device-side: nothing resolves through them once the
        id is out of ``index_of`` and the LPM."""
        # index_of is SHARED with previously-emitted snapshots: copy
        # before the first mutation (same contract as _grow_identities;
        # a second copy in a grow+retire cycle is one small dict)
        self.index_of = dict(self.index_of)
        forced: Set[Tuple[int, int, int]] = set()
        for rid in retired:
            idx = self.index_of.pop(int(rid), None)
            if idx is None:
                continue
            cls = int(self._class_of[idx])
            members = self._members.get(cls)
            if members is not None:
                members.discard(int(rid))
            if self._representative[cls] == int(rid):
                rest = self._members.get(cls) or ()
                self._representative[cls] = min(rest) if rest else -1
            if not members:
                for slot in range(len(self.endpoints)):
                    forced.add((slot, C.DIR_EGRESS, cls))
                    forced.add((slot, C.DIR_INGRESS, cls))
            stats.retired_identities += 1
        return forced

    def _ensure_port_boundaries(self, key: MapStateKey,
                                patch: SnapshotPatch) -> int:
        """Split port classes so [key.port_lo, key.port_hi] is a union of
        whole classes in the key's proto family. Appended columns copy the
        split class's cells (identical coverage before this key lands)."""
        fam = C.proto_family(key.proto)
        splits = 0
        for b in (key.port_lo, key.port_hi + 1):
            if b <= 0 or b >= 65536:
                continue
            row = self._port_table[fam]
            if row[b] != row[b - 1]:
                continue               # already a boundary
            self._own_arrays()
            row = self._port_table[fam]
            cls = int(row[b])
            span = np.nonzero(row == cls)[0]
            hi = int(span.max())
            new_cls = self._n_port_classes
            self._n_port_classes += 1
            self._port_table[fam, b:hi + 1] = new_cls
            v = self._materialize_verdict()
            self._base_verdict = np.concatenate(
                [v, v[:, :, :, cls:cls + 1]], axis=3)
            patch.full_tensors.update(("verdict", "port_class"))
            self._family_ranges = None     # re-derive at next emission
            splits += 1
        return splits

    def _split_identity(self, ident: int, idx: int,
                        patch: SnapshotPatch) -> int:
        """Move ``ident`` out of its shared class into a fresh class whose
        row starts as a copy (identical entries before this change lands)."""
        self._own_arrays()
        old_cls = int(self._class_of[idx])
        new_cls = self._n_classes
        self._n_classes += 1
        self._class_of[idx] = new_cls
        self._members[old_cls].discard(ident)
        self._members[new_cls] = {ident}
        if self._representative[old_cls] == ident:
            rest = self._members[old_cls]
            self._representative[old_cls] = min(rest) if rest else -1
        self._representative.append(ident)
        v = self._materialize_verdict()
        self._base_verdict = np.concatenate(
            [v, v[:, :, old_cls:old_cls + 1, :]], axis=2)
        patch.full_tensors.update(("verdict", "id_class_of"))
        return new_cls

    # ------------------------------------------------------------------ #
    # row resolution (the per-row ladder; mirrors policy_image._build_plane)
    # ------------------------------------------------------------------ #
    def _row_keys(self, slot: int, d: int, row: int):
        plane = self.planes[(slot, d)]
        keys = set(plane.by_ident.get(C.IDENTITY_ANY, ()))
        members = self._members.get(row)
        if members:
            # invariant: all members of a class share an identical key
            # pattern (classes split before divergence) — any member works
            rep = self._representative[row]
            keys |= {k for k in plane.by_ident.get(rep, ())}
        return keys

    def _recompute_row(self, slot: int, d: int, row: int) -> None:
        """Resolve one verdict row from the plane's mapstate and record it
        in the row overlay (a fresh array per row — frozen once emitted).
        Never touches the dense base: this is the delta path's whole write
        surface."""
        n_cols = self._base_verdict.shape[3]
        if not self._enforced_value(slot, d):
            self._overlay[(slot, d, row)] = np.full(
                (n_cols,), C.VERDICT_MISS, dtype=np.uint16)
            return
        if not self._members.get(row):
            # retired-identity tombstone (ISSUE 18): every class starts
            # with members and only retirement empties one — zero the
            # row to MISS rather than letting wildcard keys repopulate a
            # class nothing can resolve into (keeps a later whole-plane
            # recompute idempotent over dead rows)
            self._overlay[(slot, d, row)] = np.full(
                (n_cols,), C.VERDICT_MISS, dtype=np.uint16)
            return
        deny = np.zeros(n_cols, dtype=bool)
        best = np.full(n_cols, -1, dtype=np.int64)
        val = np.zeros(n_cols, dtype=np.uint16)
        plane = self.planes[(slot, d)]
        for key in self._row_keys(slot, d, row):
            entry = plane.mapstate.get(key)
            if entry is None:
                continue
            if key.proto == C.PROTO_ANY:
                cols = slice(None)
            else:
                fam = C.proto_family(key.proto)
                cols = np.unique(
                    self._port_table[fam, key.port_lo:key.port_hi + 1])
            if entry.deny:
                deny[cols] = True
                continue
            if entry.l7_rules is not None:
                cell = C.verdict_cell(C.VERDICT_REDIRECT,
                                      self.l7.intern(entry.l7_rules))
            else:
                cell = C.verdict_cell(C.VERDICT_ALLOW)
            rank = rank_scalar(key)
            if isinstance(cols, slice):
                m = rank > best
            else:
                m = rank > best[cols]
            if isinstance(cols, slice):
                best[m] = rank
                val[m] = cell
            else:
                sub = cols[m]
                best[sub] = rank
                val[sub] = cell
        out = val
        out[best < 0] = C.VERDICT_MISS
        out[deny] = C.verdict_cell(C.VERDICT_DENY)
        self._overlay[(slot, d, row)] = out

    # ------------------------------------------------------------------ #
    # snapshot emission
    # ------------------------------------------------------------------ #
    def _emit(self, revision: int, ct_config, l7_dirty: bool,
              lpm=None, ipcache: Optional[Dict[str, int]] = None,
              ipcache_revision: Optional[int] = None) -> PolicySnapshot:
        from cilium_tpu.compile.policy_image import OverlayImage
        base = self.base
        if self._overlay and len(self._overlay) <= self.rebase_rows:
            # delta emission: share the immutable base + a frozen copy of
            # the row overlay; dense access materializes lazily (the
            # serving path scatter-applies the patch and never asks)
            image = OverlayImage(self._base_verdict, dict(self._overlay),
                                 self._enforced)
        else:
            # geometry changed, overlay outgrew the rebase budget, or
            # nothing is pending: emit a plain dense image (one O(image)
            # fold at most — amortized across the delta cycles since the
            # last rebase)
            self._materialize_verdict()
            image = PolicyImage(verdict=self._base_verdict,
                                enforced=self._enforced)
        id_classes = IdentityClasses(
            identity_ids=self.identity_ids,
            index_of=self.index_of,
            class_of=self._class_of.copy(),
            n_classes=self._n_classes,
            representative=np.asarray(self._representative, dtype=np.int64))
        if self._family_ranges is None:
            self._family_ranges = _derive_family_ranges(self._port_table)
        port_classes = PortClassTable(
            table=self._port_table,
            n_classes=self._n_port_classes,
            family_class_ranges=self._family_ranges)
        l7_tensors = build_l7_tensors(self.l7) if l7_dirty else base.l7
        policies = tuple(
            EndpointPolicy(
                ep_id=ep.ep_id,
                identity_id=ep.identity_id,
                revision=revision,
                egress=DirectionPolicy(
                    self._enforced_value(slot, C.DIR_EGRESS),
                    self.planes[(slot, C.DIR_EGRESS)].mapstate),
                ingress=DirectionPolicy(
                    self._enforced_value(slot, C.DIR_INGRESS),
                    self.planes[(slot, C.DIR_INGRESS)].mapstate))
            for slot, ep in enumerate(self.endpoints))
        # working arrays are now owned by the emitted snapshot; the next
        # cycle copies before mutating (_own_arrays)
        self._arrays_owned = False
        return PolicySnapshot(
            revision=revision,
            ep_ids=base.ep_ids,
            ep_slot_of=base.ep_slot_of,
            policies=policies,
            image=image,
            id_classes=id_classes,
            port_classes=port_classes,
            lpm=lpm if lpm is not None else base.lpm,
            l7=l7_tensors,
            lb=base.lb,
            proto_family_table=base.proto_family_table,
            world_index=base.world_index,
            ct_config=ct_config or base.ct_config,
            ipcache=ipcache if ipcache is not None else base.ipcache,
            l7_interner=self.l7,
            ipcache_revision=(ipcache_revision
                              if ipcache_revision is not None
                              else base.ipcache_revision),
            services_revision=base.services_revision,
            enforcement_mode=base.enforcement_mode,
            allow_localhost=base.allow_localhost,
        )


def _derive_family_ranges(table: np.ndarray):
    """Reconstruct per-family (lo, hi) segments from the port table
    (inspection metadata; order = ascending port)."""
    fams = []
    for fam in range(table.shape[0]):
        row = table[fam]
        cuts = np.nonzero(np.diff(row))[0] + 1
        bounds = np.concatenate(([0], cuts, [65536]))
        fams.append(tuple((int(lo), int(hi - 1))
                          for lo, hi in zip(bounds[:-1], bounds[1:])))
    return tuple(fams)
