"""PolicySnapshot: one immutable, device-placeable compilation of the whole
control-plane state (the output of "the loader").

A snapshot is the unit of atomicity: the runtime double-buffers snapshots
and fences batches on snapshot revision (the analog of upstream's
per-endpoint policymap atomicity + regeneration revisions — SURVEY.md §7
"revision fencing so a batch never sees a torn policy update").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.compile.ct_layout import CTConfig
from cilium_tpu.compile.idclass import IdentityClasses, build_identity_classes
from cilium_tpu.compile.l7 import L7SetInterner, L7Tensors, build_l7_tensors
from cilium_tpu.compile.lb import LBConfig, LBTables, build_lb
from cilium_tpu.compile.lpm import LPMTables, build_lpm
from cilium_tpu.compile.policy_image import PolicyImage, build_policy_image
from cilium_tpu.compile.portclass import PortClassTable, build_port_classes
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.policy.repository import EndpointPolicy, PolicyContext, Repository
from cilium_tpu.utils import constants as C


@dataclass(frozen=True)
class PolicySnapshot:
    revision: int
    ep_ids: Tuple[int, ...]                  # slot → endpoint id
    ep_slot_of: Dict[int, int]               # endpoint id → slot
    policies: Tuple[EndpointPolicy, ...]     # slot-aligned (host/oracle use)
    image: PolicyImage
    id_classes: IdentityClasses
    port_classes: PortClassTable
    lpm: LPMTables
    l7: L7Tensors
    lb: LBTables
    proto_family_table: np.ndarray           # [256] int32
    world_index: int
    ct_config: CTConfig
    # The ipcache state this snapshot was compiled from (prefix → identity).
    # Carried so any DatapathBackend (notably the oracle-backed fake) can
    # reconstruct the exact semantics context without reaching back into the
    # live control plane.
    ipcache: Dict[str, int] = field(default_factory=dict)
    # Compile-time context for the incremental updater's geometry gates
    # (SURVEY.md §7 step 3 "diffable"): the L7 interner that numbered the
    # verdict cells' set ids, and the revisions/modes the snapshot saw.
    l7_interner: Optional[L7SetInterner] = None
    ipcache_revision: int = -1
    services_revision: int = -1
    enforcement_mode: str = C.ENFORCEMENT_DEFAULT
    allow_localhost: bool = True

    # -- device-facing view --------------------------------------------------
    def tensors(self, only: Optional[frozenset] = None
                ) -> Dict[str, np.ndarray]:
        """The flat dict of arrays the runtime places on device. Everything
        the classify kernel reads is here; scalars live in `static_config`.

        LB tensors are included only when a frontend exists: the classify
        kernel gates the whole LB stage (frontend hash probe + Maglev +
        rev-NAT gathers) on key presence, so a service-free snapshot pays
        zero per-packet LB cost (round-2 bench regression: cfg5 carried the
        full LB stage with zero services).

        ``only`` restricts the dict to the named tensors. This matters on
        the incremental fast path: a delta-emitted snapshot's dense verdict
        materializes lazily (compile/policy_image.OverlayImage), and a
        place_patch that only needs e.g. ``enforced`` must not pay an
        O(image) materialization for a tensor it never reads."""
        out: Dict[str, np.ndarray] = {}

        def want(name):
            return only is None or name in only

        if want("verdict"):
            out["verdict"] = self.image.verdict
        if want("enforced"):
            out["enforced"] = self.image.enforced
        for name, arr in (
                ("id_class_of", self.id_classes.class_of),
                ("identity_ids", self.id_classes.identity_ids),
                ("lpm_v4", self.lpm.v4_nodes),
                ("lpm_v6", self.lpm.v6_nodes),
                ("port_class", self.port_classes.table),
                ("proto_family", self.proto_family_table),
                ("l7_methods", self.l7.methods),
                ("l7_path", self.l7.path),
                ("l7_path_len", self.l7.path_len),
                ("l7_valid", self.l7.valid)):
            if want(name):
                out[name] = arr
        if self.lb.n_frontends:
            for name, arr in self.lb.tensors().items():
                if want(name):
                    out[name] = arr
        return out

    def static_config(self) -> Dict[str, int]:
        return {
            "world_index": self.world_index,
            "n_id_classes": self.id_classes.n_classes,
            "n_port_classes": self.port_classes.n_classes,
            "revision": self.revision,
        }

    @property
    def nbytes(self) -> int:
        # image.nbytes is computed without materializing a lazy
        # (delta-emitted) image; the rest are plain arrays
        n = self.image.nbytes
        for a in (self.id_classes.class_of, self.id_classes.identity_ids,
                  self.lpm.v4_nodes, self.lpm.v6_nodes,
                  self.port_classes.table, self.proto_family_table,
                  self.l7.methods, self.l7.path, self.l7.path_len,
                  self.l7.valid):
            n += a.nbytes
        if self.lb.n_frontends:
            n += sum(a.nbytes for a in self.lb.tensors().values())
        return n


def _proto_family_table() -> np.ndarray:
    table = np.full((256,), C.PROTO_FAMILY_OTHER, dtype=np.int32)
    for proto in range(256):
        table[proto] = C.proto_family(proto)
    return table


def build_snapshot(repo: Repository, ctx: PolicyContext,
                   endpoints: Sequence[Endpoint],
                   ct_config: Optional[CTConfig] = None,
                   lb_config: Optional[LBConfig] = None) -> PolicySnapshot:
    """Compile the current control-plane state for ``endpoints``.

    Mirrors the regeneration pipeline (SURVEY.md §3.2): resolve policy per
    endpoint → MapStates → dense tensors. Deterministic given (rules,
    identities, ipcache, endpoints).
    """
    policies = tuple(repo.resolve(ep) for ep in endpoints)
    ep_ids = tuple(ep.ep_id for ep in endpoints)
    ep_slot_of = {ep_id: slot for slot, ep_id in enumerate(ep_ids)}

    identity_ids = [ident.id for ident in ctx.allocator.all()]
    mapstates = []
    for slot, pol in enumerate(policies):
        mapstates.append((slot, C.DIR_EGRESS, pol.egress.mapstate))
        mapstates.append((slot, C.DIR_INGRESS, pol.ingress.mapstate))
    id_classes = build_identity_classes(identity_ids, mapstates)

    ranges_by_family: Dict[int, list] = {}
    for _slot, _d, ms in mapstates:
        for key, _entry in ms.items():
            if key.proto == C.PROTO_ANY:
                continue
            fam = C.proto_family(key.proto)
            ranges_by_family.setdefault(fam, []).append(
                (key.port_lo, key.port_hi))
    port_classes = build_port_classes(ranges_by_family)

    l7 = L7SetInterner()
    image = build_policy_image(list(policies), id_classes, port_classes, l7)
    l7_tensors = build_l7_tensors(l7)

    ipcache_snapshot = ctx.ipcache.snapshot()
    lpm = build_lpm(ipcache_snapshot, id_classes.index_of,
                    default_index=id_classes.index_of[C.IDENTITY_WORLD])

    lb = build_lb(ctx.services, lb_config)  # registry → stable rev-NAT ids

    return PolicySnapshot(
        revision=repo.revision,
        ep_ids=ep_ids,
        ep_slot_of=ep_slot_of,
        policies=policies,
        image=image,
        id_classes=id_classes,
        port_classes=port_classes,
        lpm=lpm,
        l7=l7_tensors,
        lb=lb,
        proto_family_table=_proto_family_table(),
        world_index=id_classes.index_of[C.IDENTITY_WORLD],
        ct_config=ct_config or CTConfig(),
        ipcache=ipcache_snapshot,
        l7_interner=l7,
        ipcache_revision=ctx.ipcache.revision,
        services_revision=ctx.services.revision,
        enforcement_mode=ctx.enforcement_mode,
        allow_localhost=ctx.allow_localhost,
    )
