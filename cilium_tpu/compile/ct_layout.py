"""Fixed-capacity conntrack table layout (analog of upstream
``pkg/maps/ctmap`` — SURVEY.md §2: "Becomes fixed-capacity device hash table").

Structure-of-arrays layout, power-of-two capacity, open addressing with
bounded linear probing (PROBE_DEPTH slots). No dynamic memory on device —
a saturated probe window first tail-evicts its soonest-expiring evictable
occupant (kernels/conntrack.ct_evictable: established TCP is protected),
then fails the insert: counted, and the new flow classifies DROP CT_FULL
(fail closed — exhaustion must not mint untrackable flows). A device-side
epoch sweep (kernels/conntrack.py) reclaims expired slots.

Key: 10 uint32 words — src[4] + dst[4] (16-byte normalized addresses) +
(sport<<16|dport) + (proto<<8|open_dir). An all-zero key with expiry 0 marks
an empty slot; real keys always have a nonzero proto word.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

import numpy as np

KEY_WORDS = 10
PROBE_DEPTH = 8


@dataclass
class CTConfig:
    capacity: int = 1 << 20          # 1M flows (BASELINE config 5)
    probe_depth: int = PROBE_DEPTH

    def __post_init__(self):
        if self.capacity & (self.capacity - 1):
            raise ValueError("CT capacity must be a power of two")


def make_ct_arrays(cfg: CTConfig) -> Dict[str, np.ndarray]:
    """Fresh empty table. Kept as a dict-of-arrays pytree so jit donation and
    shard_map partitioning apply uniformly."""
    cap = cfg.capacity
    return {
        "keys": np.zeros((cap, KEY_WORDS), dtype=np.uint32),
        "expiry": np.zeros((cap,), dtype=np.uint32),
        "created": np.zeros((cap,), dtype=np.uint32),
        "flags": np.zeros((cap,), dtype=np.uint32),
        "pkts_fwd": np.zeros((cap,), dtype=np.uint32),
        "pkts_rev": np.zeros((cap,), dtype=np.uint32),
        # service rev-NAT: stable rev-NAT id + 1 of the DNAT applied at
        # create time (see compile/lb.LBTables — stable ids are why stale CT
        # entries fail closed instead of rewriting to another service's VIP),
        # 0 = none (upstream: CtEntry.rev_nat_index)
        "rev_nat": np.zeros((cap,), dtype=np.uint32),
    }
