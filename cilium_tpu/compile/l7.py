"""L7-lite rule-set tensors (BASELINE config 4, the envoy-bypass path).

Each distinct frozenset of HTTPRules is interned to a 1-based set id (0 = "no
redirect"); ids are what verdict cells and CT entries carry. The tensors let
the device match a tokenized request (method id, padded path bytes) against
every rule of a set with one vectorized compare:

  methods   [n_sets+1, R]      uint8   (255 = any method)
  path      [n_sets+1, R, 64]  uint8   (prefix bytes, zero-padded)
  path_len  [n_sets+1, R]      int32
  valid     [n_sets+1, R]      bool

match(set_id, m, p) = any_r(valid & (methods==255|methods==m)
                            & prefix_eq(path[r], p, path_len[r]))
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Tuple

import numpy as np

from cilium_tpu.model.rules import HTTPRule
from cilium_tpu.utils import constants as C


class L7SetInterner:
    def __init__(self):
        self._index: Dict[FrozenSet[HTTPRule], int] = {}
        self.sets: List[FrozenSet[HTTPRule]] = []

    def intern(self, rules: FrozenSet[HTTPRule]) -> int:
        idx = self._index.get(rules)
        if idx is None:
            self.sets.append(rules)
            idx = len(self.sets)           # 1-based; 0 = none
            self._index[rules] = idx
        return idx

    def known(self, rules: FrozenSet[HTTPRule]):
        """Set id if already interned, else None (non-mutating — the
        incremental updater's geometry gate: a new set would grow the L7
        tensors, which is a full-rebuild event)."""
        return self._index.get(rules)


@dataclass(frozen=True)
class L7Tensors:
    methods: np.ndarray     # [n_sets+1, R] uint8
    path: np.ndarray        # [n_sets+1, R, L7_PATH_MAXLEN] uint8
    path_len: np.ndarray    # [n_sets+1, R] int32
    valid: np.ndarray       # [n_sets+1, R] bool
    n_sets: int

    @property
    def max_rules(self) -> int:
        return self.methods.shape[1]


def build_l7_tensors(interner: L7SetInterner) -> L7Tensors:
    n_sets = len(interner.sets)
    max_rules = max((len(s) for s in interner.sets), default=1) or 1
    L = C.L7_PATH_MAXLEN
    methods = np.full((n_sets + 1, max_rules), C.HTTP_METHOD_ANY, dtype=np.uint8)
    path = np.zeros((n_sets + 1, max_rules, L), dtype=np.uint8)
    path_len = np.zeros((n_sets + 1, max_rules), dtype=np.int32)
    valid = np.zeros((n_sets + 1, max_rules), dtype=bool)
    for set_id, rules in enumerate(interner.sets, start=1):
        # deterministic rule order (matching is any(), order irrelevant, but
        # determinism keeps snapshots diffable)
        ordered = sorted(rules, key=lambda h: (h.method, h.path))
        for r, rule in enumerate(ordered):
            methods[set_id, r] = (C.HTTP_METHOD_IDS[rule.method]
                                  if rule.method else C.HTTP_METHOD_ANY)
            pb = rule.path.encode()
            path[set_id, r, :len(pb)] = np.frombuffer(pb, dtype=np.uint8)
            path_len[set_id, r] = len(pb)
            valid[set_id, r] = True
    return L7Tensors(methods=methods, path=path, path_len=path_len,
                     valid=valid, n_sets=n_sets)


def l7_match_host(t: L7Tensors, set_id: int, method: int, path: bytes) -> bool:
    """Host reference of the tensor match (tests; must agree with
    oracle.datapath.l7_match and the jnp kernel)."""
    if set_id <= 0:
        return True
    pbuf = np.zeros(C.L7_PATH_MAXLEN, dtype=np.uint8)
    pb = path[:C.L7_PATH_MAXLEN]
    pbuf[:len(pb)] = np.frombuffer(pb, dtype=np.uint8)
    for r in range(t.max_rules):
        if not t.valid[set_id, r]:
            continue
        m = t.methods[set_id, r]
        if m != C.HTTP_METHOD_ANY and m != method:
            continue
        n = int(t.path_len[set_id, r])
        if n > len(path):
            continue
        if (t.path[set_id, r, :n] == pbuf[:n]).all():
            return True
    return False
