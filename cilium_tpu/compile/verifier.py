"""Compile-all-configs verifier (SURVEY.md §4: upstream compiles every bpf
object for all kernel/config combos in ``test/verifier`` CI and asserts
verifier acceptance — "analog: assert XLA compilation of every config combo,
HBM budget check").

Here the eBPF verifier's role is played by XLA: a datapath configuration is
"verifier-accepted" when its fused classify program lowers, compiles, and
fits the memory budget. ``verify_configs`` AOT-compiles the classify step
over the cross product of datapath shape knobs (address family, wire format,
L7, LB, CT geometry, rule-shard padding) on tiny worlds and reports
per-combo status + compiled memory use, failing loudly on any combo a code
change broke — BEFORE that combo is hit in production.

Run via ``cilium-tpu verify`` or pytest (tests/test_verifier.py).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


@dataclass
class ComboReport:
    name: str
    ok: bool
    error: str = ""
    argument_bytes: int = 0
    temp_bytes: int = 0
    output_bytes: int = 0


def _build_world(l7: bool, lb: bool, v6: bool):
    from cilium_tpu.compile.ct_layout import CTConfig
    from cilium_tpu.compile.snapshot import build_snapshot
    from cilium_tpu.model.endpoint import Endpoint
    from cilium_tpu.model.identity import IdentityAllocator
    from cilium_tpu.model.ipcache import IPCache
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule
    from cilium_tpu.model.services import Service
    from cilium_tpu.policy import PolicyContext, Repository
    from cilium_tpu.policy.selectorcache import SelectorCache
    from cilium_tpu.model.services import ServiceRegistry

    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache(), services=ServiceRegistry())
    repo = Repository(ctx)
    lbls = Labels.parse(["k8s:app=web"])
    ident = alloc.allocate(lbls)
    ctx.ipcache.upsert("192.168.0.10/32", ident.id)
    ep = Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)
    docs = [{"endpointSelector": {"matchLabels": {"app": "web"}},
             "egress": [{"toCIDR": ["10.0.0.0/8"],
                         "toPorts": [{"ports": [
                             {"port": "443", "protocol": "TCP"}]}]}]}]
    if v6:
        docs.append({"endpointSelector": {"matchLabels": {"app": "web"}},
                     "egress": [{"toCIDR": ["2001:db8::/32"]}]})
    if l7:
        docs.append({"endpointSelector": {"matchLabels": {"app": "web"}},
                     "ingress": [{"toPorts": [{
                         "ports": [{"port": "80", "protocol": "TCP"}],
                         "rules": {"http": [
                             {"method": "GET", "path": "/api"}]}}]}]})
    if lb:
        # a REAL frontend: the snapshot only carries LB tensors (and the
        # kernel only compiles the LB stage — frontend probe, Maglev,
        # rev-NAT) when one exists; a frontend-less service would make
        # every "+lb" combo compile the identical LB-free program
        from cilium_tpu.model.services import Backend, Frontend
        from cilium_tpu.utils import constants as CC
        ctx.services.upsert(Service(
            name="api", namespace="prod", backends=("10.3.0.1",),
            frontends=(Frontend("10.96.0.10", 443, CC.PROTO_TCP),),
            lb_backends=(Backend("10.3.0.1", 8443),)))
        docs.append({"endpointSelector": {"matchLabels": {"app": "web"}},
                     "egress": [{"toServices": [{"k8sService": {
                         "serviceName": "api", "namespace": "prod"}}]}]})
    repo.add([parse_rule(d) for d in docs])
    return build_snapshot(repo, ctx, [ep], CTConfig(capacity=1 << 10))


def memory_stats(compiled) -> Dict[str, int]:
    """Bytes a compiled XLA executable needs, via ``memory_analysis()`` —
    the machinery both the offline budget check here and the live HBM
    ledger (runtime/datapath.hbm_ledger, ISSUE 13) cite: argument bytes are
    the placed tensors the ledger accounts group by group; temp bytes are
    the compiler's scratch on top."""
    try:
        m = compiled.memory_analysis()
        return {
            "argument_bytes": int(getattr(m, "argument_size_in_bytes", 0)),
            "temp_bytes": int(getattr(m, "temp_size_in_bytes", 0)),
            "output_bytes": int(getattr(m, "output_size_in_bytes", 0)),
        }
    except Exception:
        return {"argument_bytes": 0, "temp_bytes": 0, "output_bytes": 0}


_memory_stats = memory_stats           # pre-ISSUE-13 private name


def budget_doc(reports: List[ComboReport],
               max_hbm_bytes: Optional[int] = None) -> Dict:
    """Summarize one verify sweep into the HBM budget report that
    ``status_doc`` and bench-artifact provenance embed (ISSUE 13 satellite:
    offline ``--max-hbm-bytes`` verification and the live ledger citing
    the same numbers). Pure function of the reports — reusable on a sweep
    loaded back from a ``cilium-tpu verify --report`` file."""
    ok = [r for r in reports if r.ok]
    worst = max(ok, key=lambda r: r.argument_bytes + r.temp_bytes,
                default=None)
    return {
        "combos": len(reports),
        "accepted": len(ok),
        "rejected": [r.name for r in reports if not r.ok],
        "max_hbm_bytes": max_hbm_bytes,
        "worst_combo": worst.name if worst is not None else None,
        "worst_argument_bytes": worst.argument_bytes if worst else 0,
        "worst_temp_bytes": worst.temp_bytes if worst else 0,
        "worst_total_bytes": (worst.argument_bytes + worst.temp_bytes)
        if worst else 0,
    }


def verify_configs(batch: int = 256,
                   max_hbm_bytes: Optional[int] = None,
                   quick: bool = False) -> List[ComboReport]:
    """AOT-compile the classify step for every datapath shape combo.
    ``max_hbm_bytes`` bounds argument+temp memory per combo (HBM budget
    check; None = report only). ``quick`` drops the LB axis (the LB stage's
    program shape is covered by the full sweep in CI; quick keeps the
    family/wire/L7 axes that actually change lowering)."""
    import jax
    import jax.numpy as jnp
    from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
    from cilium_tpu.kernels.classify import make_classify_fn
    from cilium_tpu.kernels.records import (
        empty_batch, pack_batch, pack_batch_l7dict, pack_batch_v4)

    reports: List[ComboReport] = []
    wire_formats = ("dict", "v4", "full", "l7dict", "addr")
    lb_axis = (False,) if quick else (False, True)
    for v4_only, l7, lb, wire in itertools.product(
            (False, True), (False, True), lb_axis, wire_formats):
        if wire == "v4" and (l7 or not v4_only):
            continue                    # compact wire is v4/L7-free only
        if wire == "l7dict" and not l7:
            continue
        if wire == "addr" and (v4_only or lb):
            continue                    # one addr-dict combo per L7 state
        name = (f"{'v4only' if v4_only else 'dual'}"
                f"{'+l7' if l7 else ''}{'+lb' if lb else ''}+{wire}")
        try:
            snap = _build_world(l7=l7, lb=lb, v6=not v4_only)
            tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
            ct = {k: jnp.asarray(v) for k, v in make_ct_arrays(
                snap.ct_config).items()}
            b = empty_batch(batch)
            b["valid"][:] = True
            b["dst"][:, 2] = 0xFFFF
            b["dst"][:, 3] = 0x0A000001
            if l7:
                b["http_method"][:] = 0
                b["http_path"][:, 0] = ord("/")
            fn = make_classify_fn(v4_only=v4_only, donate_ct=False,
                                  packed=wire != "dict")
            if wire == "dict":
                arg = {k: jnp.asarray(v) for k, v in b.items()}
            elif wire == "v4":
                arg = jnp.asarray(pack_batch_v4(b))
            elif wire == "l7dict":
                w, d = pack_batch_l7dict(b)
                arg = (jnp.asarray(w), jnp.asarray(d))
            elif wire == "addr":
                from cilium_tpu.kernels.records import pack_batch_addrdict
                arg = tuple(jnp.asarray(x)
                            for x in pack_batch_addrdict(b, l7=l7))
            else:
                arg = jnp.asarray(pack_batch(b, l7=l7))
            lowered = fn.lower(tensors, ct, arg, jnp.uint32(1000),
                               jnp.int32(snap.world_index))
            compiled = lowered.compile()
            stats = _memory_stats(compiled)
            reports.append(ComboReport(name=name, ok=True, **stats))
        except Exception as e:          # compile failure = verifier reject
            reports.append(ComboReport(name=name, ok=False, error=repr(e)))
    # the sharded program (rule-axis psum) is covered by dryrun_multichip;
    # here we additionally verify rule-padded single-device geometry
    try:
        from cilium_tpu.parallel.mesh import pad_snapshot_tensors
        snap = _build_world(l7=False, lb=False, v6=False)
        tensors_np = pad_snapshot_tensors(snap.tensors(), 4)
        tensors = {k: jnp.asarray(v) for k, v in tensors_np.items()}
        ct = {k: jnp.asarray(v) for k, v in make_ct_arrays(
            snap.ct_config).items()}
        b = empty_batch(batch)
        fn = make_classify_fn(v4_only=True, donate_ct=False)
        arg = {k: jnp.asarray(v) for k, v in b.items()}
        compiled = fn.lower(tensors, ct, arg, jnp.uint32(1000),
                            jnp.int32(snap.world_index)).compile()
        reports.append(ComboReport(name="rule-padded", ok=True,
                                   **_memory_stats(compiled)))
    except Exception as e:
        reports.append(ComboReport(name="rule-padded", ok=False,
                                   error=repr(e)))
    if max_hbm_bytes is not None:
        reports = apply_budget(reports, max_hbm_bytes)
    return reports


def apply_budget(reports: List[ComboReport],
                 max_hbm_bytes: int) -> List[ComboReport]:
    """Post-process a sweep's memory stats against an HBM budget — pure
    function of the reports, so one compile sweep serves any number of
    budget policies (CI reuses a single sweep)."""
    import dataclasses
    out = []
    for r in reports:
        total = r.argument_bytes + r.temp_bytes
        if r.ok and total > max_hbm_bytes:
            r = dataclasses.replace(
                r, ok=False,
                error=f"memory budget exceeded: {total} > {max_hbm_bytes}")
        else:
            r = dataclasses.replace(r)   # never alias the input reports
        out.append(r)
    return out
