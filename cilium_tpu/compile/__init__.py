"""The tensor compiler — "the loader" (analog of upstream
``pkg/datapath/loader``; SURVEY.md §2: "Replace with rule→tensor compiler +
jit cache; this is the plugin boundary kept intact").

Lowers host control-plane state into dense device tensor images:

- ``lpm.py``         — ipcache snapshot → stride-8 multibit-trie tensors
- ``portclass.py``   — L4 port ranges → per-proto-family equivalence classes
- ``idclass.py``     — identities → equivalence classes over MapState rows
- ``policy_image.py``— MapState → dense ``verdict[id_class, port_class]``
                       (the whole precedence ladder resolved at compile time)
- ``l7.py``          — L7-lite http rule sets → token-match tensors
- ``ct_layout.py``   — fixed-capacity conntrack table array layout
- ``snapshot.py``    — PolicySnapshot: one immutable, device-placeable bundle
"""

from cilium_tpu.compile.snapshot import PolicySnapshot, build_snapshot

__all__ = ["PolicySnapshot", "build_snapshot"]
