"""Pipeline guard layer: overload protection + self-healing for the hot path.

PR 1 gave the *control plane* supervised degradation (last-good snapshots,
backoff, OK/DEGRADED/STALE health) and the scheduler gave the pipeline
retry-on-fault — but until this layer the serving path still failed
unboundedly: a hung ``dispatch_fn``/``finalize`` (device stall) wedged the
worker forever with every ticket blocked, a worker crash closed the
pipeline permanently, admitted work had no deadline so a backlog served
arbitrarily stale submissions, and repeated dispatch errors kept hammering
a sick backend. This module holds the three mechanisms the scheduler wires
into its hot path to extend the supervised-degradation philosophy there:

- **Error taxonomy** — every way a submission can fail is a distinct
  ``PipelineError`` subclass, so the serving surface (REST/CLI) can map
  overload shed (:class:`PipelineDrop`, :class:`PipelineDeadlineExceeded`
  → 429) apart from unavailability (:class:`PipelineUnavailable`,
  :class:`PipelineClosed` → 503).
- :class:`CircuitBreaker` — consecutive dispatch/finalize failures past a
  threshold open the breaker; submissions then fail fast with
  :class:`PipelineUnavailable` instead of burning per-submission retry
  budgets against a sick backend. After ``cooldown_s`` one *probe*
  submission is admitted (half-open); its dispatch succeeding closes the
  breaker, failing re-opens it. Transitions are traced
  (``pipeline.breaker`` events), counted
  (``pipeline_breaker_transitions_total{to=...}``) and gauged
  (``pipeline_breaker_state``).
- :class:`Watchdog` — a supervisor thread fed by worker heartbeats (armed
  around each blocking dispatch/finalize call). A heartbeat armed longer
  than ``stall_timeout_s`` means the worker is wedged in the device path;
  the watchdog then drives the scheduler's restart protocol: reject the
  wedged in-flight window, abandon the stuck thread behind a generation
  fence, and start a fresh worker on a fresh staging ring. Restarts are
  bounded with capped backoff; past the bound the pipeline goes
  *hard-failed* (every submission rejected fast) rather than flapping.

The scheduler (``pipeline/scheduler.py``) owns the wiring; everything here
is mechanism, deliberately free of scheduler imports so the error types
can be shared across layers (engine, API, CLI) without cycles.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

log = logging.getLogger("cilium_tpu.pipeline.guard")

#: pipeline serving states surfaced through stats()/health()/Prometheus
#: (gauge ``pipeline_state`` carries the numeric code)
PIPELINE_STATES: Dict[str, int] = {
    "ok": 0, "breaker-open": 1, "restarting": 2, "failed": 3, "closed": 4,
    "device-lost": 5,
}

#: breaker states → ``pipeline_breaker_state`` gauge codes
BREAKER_STATES: Dict[str, int] = {"closed": 0, "half-open": 1, "open": 2}

#: overload-ladder states (the supervised degradation ladder under
#: adversarial load — OverloadLadder below) → ``overload_state`` gauge
#: codes. Each rung arms one more shedding behavior; see the README
#: "Failure modes & degradation" table for the full contract.
OVERLOAD_OK = 0
OVERLOAD_PRESSURE = 1
OVERLOAD_OVERLOAD = 2
OVERLOAD_SHED_NEW = 3
OVERLOAD_STATES: Dict[str, int] = {
    "ok": OVERLOAD_OK, "pressure": OVERLOAD_PRESSURE,
    "overload": OVERLOAD_OVERLOAD, "shed-new": OVERLOAD_SHED_NEW,
}
OVERLOAD_STATE_NAMES: Dict[int, str] = {v: k for k, v in
                                        OVERLOAD_STATES.items()}

#: priority classes the shim feeder stamps into the ``_prio`` batch column
#: (lower = more important). Established-CT flows outrank new flows, which
#: outrank unknown-endpoint traffic — the shedding order under PRESSURE+.
PRIO_ESTABLISHED = 0
PRIO_NEW = 1
PRIO_UNKNOWN = 2


class PipelineError(RuntimeError):
    """Base error for pipeline submissions."""


class PipelineDrop(PipelineError):
    """Submission shed at admission (queue full, drop mode or block
    timeout exhausted). Overload shed → retryable (429 at the API)."""


class PipelineClosed(PipelineError):
    """submit() after close()/stop()."""


class PipelineDeadlineExceeded(PipelineError):
    """Submission shed because its deadline passed before the worker
    reached it (at ingest) or before its microbatch dispatched (at
    flush). The answer nobody is waiting for is never computed."""


class PipelineUnavailable(PipelineError):
    """Fail-fast rejection: the circuit breaker is open, or the pipeline
    hard-failed after exhausting its watchdog restart budget. 503 at the
    API — the backend is sick, not merely busy."""


class DeviceLost(RuntimeError):
    """A dispatch failed with a dead-accelerator signature — not the
    transient breaker/backoff territory every other dispatch error lands
    in, but a chip that left the mesh (runtime/datapath.dead_device_of is
    the classifier that tells the two apart). ``device`` is the ordinal
    into the datapath's CONFIGURED device list (-1 = a device died but
    the error named no ordinal; the engine probes to attribute it).

    Deliberately NOT a :class:`PipelineError`: the scheduler treats it as
    a mesh-health signal (park the worker, notify the engine's re-mesh
    path) rather than a per-submission failure, and only the wedged
    in-flight window is rejected — queued submissions survive the fenced
    re-mesh, exactly like a watchdog restart."""

    def __init__(self, message: str, device: int = -1):
        super().__init__(message)
        self.device = device


class PipelineTenantCap(PipelineDrop):
    """Per-tenant occupancy-cap shed (multi-tenant QoS): the submitter is
    at its OWN queue budget while the shared queue may still have room —
    isolation working as designed, not a cluster-wide overload. A
    :class:`PipelineDrop` subclass, so every existing retryable-429
    handler treats it correctly without knowing about tenants."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the dispatch path.

    Thread-safe and self-contained: the scheduler calls
    :meth:`record_failure` / :meth:`record_success` from the worker and
    :meth:`admit` from producers; ``on_transition`` (if given) fires on
    every state change with ``(old, new)`` so the owner can fold the state
    into its own health surface."""

    def __init__(self, threshold: int = 20, cooldown_s: float = 5.0, *,
                 metrics=None, tracer=None, name: str = "pipeline",
                 on_transition: Optional[Callable[[str, str], None]] = None):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("breaker cooldown must be > 0")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.metrics = metrics
        self.tracer = tracer
        self.name = name
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_mono = 0.0
        self._probe_at: Optional[float] = None   # a half-open probe is out
        self._transitions = 0

    # -- producer side -------------------------------------------------------
    def admit(self) -> bool:
        """One admission decision. ``True`` → let the submission in
        (normal serving, or the half-open probe); ``False`` → fail fast."""
        moved = None
        with self._lock:
            now = time.monotonic()
            if self._state == "closed":
                return True
            if self._state == "open":
                if now - self._opened_mono >= self.cooldown_s:
                    moved = self._transition_locked("half-open")
                    self._probe_at = now
                    verdict = True
                else:
                    verdict = False
            # half-open: one probe at a time; a probe that never reported
            # back (admission dropped it downstream) expires after a
            # cooldown so the breaker cannot wedge itself shut
            elif self._probe_at is None or now - self._probe_at >= \
                    self.cooldown_s:
                self._probe_at = now
                verdict = True
            else:
                verdict = False
        self._emit(moved)
        return verdict

    # -- worker side ---------------------------------------------------------
    def record_failure(self) -> bool:
        """One dispatch/finalize failure. Returns True when the breaker is
        now open (the caller should stop retrying and reject fast)."""
        moved = None
        with self._lock:
            self._consecutive += 1
            self._probe_at = None
            if self._state == "half-open":
                moved = self._transition_locked("open")   # the probe failed
                self._opened_mono = time.monotonic()
            elif self._state == "closed" and \
                    self._consecutive >= self.threshold:
                moved = self._transition_locked("open")
                self._opened_mono = time.monotonic()
            now_open = self._state == "open"
        self._emit(moved)
        return now_open

    def record_success(self) -> None:
        moved = None
        with self._lock:
            self._consecutive = 0
            self._probe_at = None
            if self._state != "closed":
                # the probe came back healthy
                moved = self._transition_locked("closed")
        self._emit(moved)

    # -- internals -----------------------------------------------------------
    def _transition_locked(self, to: str) -> Tuple[str, str, int]:
        """Lock held: flip the state; the observable side effects happen
        in :meth:`_emit` after the lock is released (``on_transition`` may
        take the owner's lock — holding ours across it would invert lock
        order against readers of :attr:`state`)."""
        old, self._state = self._state, to
        self._transitions += 1
        return (old, to, self._consecutive)

    def _emit(self, moved: Optional[Tuple[str, str, int]]) -> None:
        if moved is None:
            return
        old, to, consecutive = moved
        log.warning("%s circuit breaker %s -> %s (%d consecutive failures)",
                    self.name, old, to, consecutive)
        if self.metrics is not None:
            self.metrics.inc_counter(
                f'pipeline_breaker_transitions_total{{to="{to}"}}')
            self.metrics.set_gauge("pipeline_breaker_state",
                                   BREAKER_STATES[to])
        if self.tracer is not None:
            self.tracer.event("pipeline.breaker", frm=old, to=to,
                              consecutive=consecutive)
        if self._on_transition is not None:
            self._on_transition(old, to)

    # -- read side -----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> Dict:
        with self._lock:
            d = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "transitions": self._transitions,
            }
            if self._state == "open":
                d["retry_in_s"] = round(max(
                    0.0, self.cooldown_s
                    - (time.monotonic() - self._opened_mono)), 3)
            return d


class OverloadLadder:
    """The explicit degradation state machine under adversarial load:
    OK → PRESSURE → OVERLOAD → SHED-NEW.

    Pure mechanism (no pipeline/engine imports): the owner feeds it one
    ``observe(queue_frac, shed_rate, ct_occupancy)`` per control interval
    — queue occupancy fraction, sheds+admission-drops per second, CT live
    fraction — and propagates the returned state to the shedding sites
    (``Pipeline.set_overload_state``, ``ShimFeeder.set_overload_state``).

    Mechanics: each input is a *latched* signal with per-signal hysteresis
    (lights at its high threshold, stays lit until it falls below its low
    threshold), and the lit count is the severity: one lit signal holds
    PRESSURE; two-or-more lit signals keep ESCALATING — one rung per
    ``up_ticks`` consecutive pressured intervals, all the way to SHED-NEW
    if the pressure survives each stronger shed (requiring all three
    would deadlock: fail-fast admission at OVERLOAD is precisely what
    keeps the CT signal from ever lighting in an ingest-bound storm).
    Descent is one rung per ``down_ticks`` calm intervals and
    deliberately slow — a storm pausing for one scrape must not whiplash
    the feeder back into full admission.

    Thread-safe; ``status()`` carries per-state dwell times (the cfg6
    bench's ladder-residency surface) and the last observed inputs."""

    #: bounded transition trail for status()/debug bundles
    MAX_TRANSITIONS = 32

    def __init__(self, *, queue_high: float = 0.75, queue_low: float = 0.25,
                 shed_high: float = 50.0, shed_low: float = 5.0,
                 ct_high: float = 0.85, ct_low: float = 0.6,
                 resource_high: float = 0.9, resource_low: float = 0.7,
                 up_ticks: int = 2, down_ticks: int = 6):
        if not (0.0 <= queue_low < queue_high <= 1.0):
            raise ValueError("need 0 <= queue_low < queue_high <= 1")
        if not (0.0 <= shed_low < shed_high):
            raise ValueError("need 0 <= shed_low < shed_high")
        if not (0.0 <= ct_low < ct_high <= 1.0):
            raise ValueError("need 0 <= ct_low < ct_high <= 1")
        if not (0.0 <= resource_low < resource_high <= 1.0):
            raise ValueError("need 0 <= resource_low < resource_high <= 1")
        if up_ticks < 1 or down_ticks < 1:
            raise ValueError("up_ticks and down_ticks must be >= 1")
        self._hi = {"queue": queue_high, "shed": shed_high, "ct": ct_high,
                    "resource": resource_high}
        self._lo = {"queue": queue_low, "shed": shed_low, "ct": ct_low,
                    "resource": resource_low}
        self._up_ticks = up_ticks
        self._down_ticks = down_ticks
        self._lock = threading.Lock()
        self._lit = {"queue": False, "shed": False, "ct": False,
                     "resource": False}
        self._last: Dict[str, float] = {}
        self.state = 0
        self._up = 0
        self._down = 0
        self._entered_mono = time.monotonic()
        self._dwell = [0.0, 0.0, 0.0, 0.0]
        self.transitions = 0
        self._trail: list = []

    def _latch(self, name: str, value: float) -> bool:
        if value >= self._hi[name]:
            self._lit[name] = True
        elif value <= self._lo[name]:
            self._lit[name] = False
        return self._lit[name]

    def observe(self, queue_frac: float, shed_rate: float,
                ct_occupancy: float,
                resource_pressure: float = 0.0) -> Tuple[int, bool]:
        """One control interval. Returns (state, changed).
        ``resource_pressure`` (ISSUE 13) is the resource ledger's worst
        non-CT pressure fraction — a fourth latch, so a wire pool / patch
        budget / ring running hot counts toward severity exactly like the
        original three signals (default 0.0 keeps three-signal callers'
        behavior bit-identical)."""
        with self._lock:
            sev = sum((self._latch("queue", queue_frac),
                       self._latch("shed", shed_rate),
                       self._latch("ct", ct_occupancy),
                       self._latch("resource", resource_pressure)))
            self._last = {"queue_frac": round(queue_frac, 4),
                          "shed_rate": round(shed_rate, 2),
                          "ct_occupancy": round(ct_occupancy, 4),
                          "resource_pressure": round(resource_pressure, 4),
                          "severity": sev}
            old = self.state
            # SHED-NEW is the top rung: with four latchable signals the
            # severity can reach 4, and an unbounded climb would step past
            # the state table exactly when shedding matters most
            escalate = (self.state < OVERLOAD_SHED_NEW
                        and (sev > self.state or sev >= 2))
            calm = sev < self.state and sev < 2
            if escalate:
                self._up += 1
                self._down = 0
                if self._up >= self._up_ticks:
                    self._move_locked(self.state + 1)
                    self._up = 0
            elif calm:
                self._down += 1
                self._up = 0
                if self._down >= self._down_ticks:
                    self._move_locked(self.state - 1)
                    self._down = 0
            else:
                self._up = self._down = 0
            return self.state, self.state != old

    def _move_locked(self, to: int) -> None:
        now = time.monotonic()
        self._dwell[self.state] += now - self._entered_mono
        self._entered_mono = now
        self._trail.append({"t": time.time(),
                            "frm": OVERLOAD_STATE_NAMES[self.state],
                            "to": OVERLOAD_STATE_NAMES[to],
                            "inputs": dict(self._last)})
        del self._trail[:-self.MAX_TRANSITIONS]
        self.state = to
        self.transitions += 1
        log.warning("overload ladder %s -> %s (%s)",
                    self._trail[-1]["frm"], self._trail[-1]["to"],
                    self._last)

    def status(self) -> Dict:
        with self._lock:
            now = time.monotonic()
            dwell = list(self._dwell)
            dwell[self.state] += now - self._entered_mono
            return {
                "state": OVERLOAD_STATE_NAMES[self.state],
                "level": self.state,
                "since_s": round(now - self._entered_mono, 3),
                "dwell_s": {OVERLOAD_STATE_NAMES[i]: round(d, 3)
                            for i, d in enumerate(dwell)},
                "transitions": self.transitions,
                "trail": list(self._trail),
                "inputs": dict(self._last),
                "lit": dict(self._lit),
            }


class Watchdog:
    """Supervisor thread watching the worker's heartbeat.

    ``heartbeat()`` returns the worker's currently armed beat as
    ``(armed_mono, label, gen, grace)`` or None when the worker is not
    inside a blocking call (an idle worker parked on its condvar is
    healthy, not stalled). ``grace`` is a per-beat multiplier on the stall
    budget — a cold first dispatch (XLA compile) gets more rope than a
    warm one. When a beat stays armed past ``stall_timeout_s × grace``
    the watchdog calls ``on_stall(gen, reason)`` — the scheduler's
    restart protocol, which is generation-fenced so a double fire is a
    no-op.
    ``should_stop()`` True ends the thread (pipeline closed/hard-failed).

    ``stall_timeout_s`` is mutable at runtime (the chaos driver shrinks it
    after XLA warmup so a stall-storm scenario doesn't have to out-wait a
    production-sized timeout)."""

    def __init__(self, *, stall_timeout_s: float,
                 heartbeat: Callable[
                     [], Optional[Tuple[float, str, int, int]]],
                 on_stall: Callable[[int, str], None],
                 should_stop: Callable[[], bool],
                 name: str = "pipeline"):
        if stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be > 0")
        self.stall_timeout_s = stall_timeout_s
        self._heartbeat = heartbeat
        self._on_stall = on_stall
        self._should_stop = should_stop
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-watchdog")

    def start(self) -> None:
        self._thread.start()

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def _run(self) -> None:
        while True:
            # re-derive each lap: stall_timeout_s is runtime-tunable
            time.sleep(max(0.005, min(self.stall_timeout_s / 4.0, 0.25)))
            if self._should_stop():
                return
            beat = self._heartbeat()
            if beat is None:
                continue
            armed_mono, label, gen, grace = beat
            budget = self.stall_timeout_s * max(1, grace)
            stalled_for = time.monotonic() - armed_mono
            if stalled_for > budget:
                try:
                    self._on_stall(gen, f"worker stalled in {label} for "
                                        f"{stalled_for:.2f}s (timeout "
                                        f"{budget}s)")
                except Exception:        # noqa: BLE001 — never kill the dog
                    log.exception("watchdog restart attempt failed")
