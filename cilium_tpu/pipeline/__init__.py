"""Pipelined ingestion scheduler: overlapped host→device batching runtime.

See :mod:`cilium_tpu.pipeline.scheduler` for the design and
:mod:`cilium_tpu.pipeline.guard` for the overload-protection/self-healing
layer (deadlines, circuit breaker, watchdog-supervised restart).
"""

from cilium_tpu.pipeline.guard import (CircuitBreaker, PipelineClosed,
                                       PipelineDeadlineExceeded,
                                       PipelineDrop, PipelineError,
                                       PipelineTenantCap,
                                       PipelineUnavailable, Watchdog)
from cilium_tpu.pipeline.scheduler import Pipeline, Ticket

__all__ = ["CircuitBreaker", "Pipeline", "PipelineClosed",
           "PipelineDeadlineExceeded", "PipelineDrop", "PipelineError",
           "PipelineTenantCap", "PipelineUnavailable", "Ticket", "Watchdog"]
