"""Pipelined ingestion scheduler: overlapped host→device batching runtime.

See :mod:`cilium_tpu.pipeline.scheduler` for the design.
"""

from cilium_tpu.pipeline.scheduler import (Pipeline, PipelineClosed,
                                           PipelineDrop, PipelineError,
                                           Ticket)

__all__ = ["Pipeline", "PipelineClosed", "PipelineDrop", "PipelineError",
           "Ticket"]
