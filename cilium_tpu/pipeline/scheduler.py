"""Pipelined ingestion scheduler: overlapped host→device batching runtime.

BENCH_r05 showed the serving path ~30-60x off its own compute ceiling:
``compute_only`` runs ~300M flows/sec/chip while the end-to-end path sits at
6-9M, because a batch is built, transferred, and classified strictly
serially. This subsystem is the continuous-batching layer between the shim
and the datapath that closes that shape problem:

- **Admission with backpressure** (``submit``): a bounded multi-producer
  queue. When full, producers either block up to a timeout or shed
  immediately (``admission="drop"``) — never unbounded blocking, and every
  shed submission is accounted (``pipeline_admission_drops_total``).
- **Deadline-based microbatching**: sub-full submissions coalesce in a host
  staging buffer until either the buffer fills or the oldest submission's
  deadline (``flush_ms``) expires. Dispatch shapes are drawn from a small
  set of power-of-two buckets in ``[min_bucket, max_bucket]`` so the device
  sees a handful of stable shapes (no recompile storms). A submission that
  already *is* a bucket shape bypasses staging entirely (zero-copy
  ``direct`` dispatch).
- **Overlap** (double/ring-buffered staging): dispatch goes through
  ``DatapathBackend.classify_async`` — the JIT backend enqueues pack +
  transfer + XLA dispatch and returns a finalize callable, so the worker
  stages and transfers batch *i+1* while the device still computes batch
  *i* (up to ``inflight`` batches in flight; CT buffer donation sequences
  the steps on-device). On FakeDatapath classify_async is synchronous — a
  plain queue, same semantics, no overlap.
- **Ordering**: one worker drains the queue FIFO and finalizes in-flight
  batches FIFO, so CT mutation order == submission order and every ticket
  resolves in order. This is what makes pipeline verdicts bit-identical to
  the serial ``classify`` path on the same submissions.
- **Telemetry**: queue depth / inflight gauges, admission drops, flush
  reasons, fill ratio, and ``pipeline_queue_wait_seconds`` /
  ``pipeline_batch_latency_seconds`` histograms through ``Metrics``.

Overload protection & self-healing (the guard layer, ``pipeline/guard.py``):

- **Per-submission deadlines**: ``submit(deadline_ms=...)`` rides the
  ticket; the worker sheds already-stale work at ingest and at flush time
  (rejected with :class:`PipelineDeadlineExceeded`, counted per reason in
  ``pipeline_shed_total{reason}``) so a backlog never burns device time on
  answers nobody is waiting for.
- **Priority shedding** (the overload ladder's PRESSURE behavior,
  ``pipeline/guard.OverloadLadder`` — armed via
  :meth:`Pipeline.set_overload_state`): with the queue full, a submission
  that outranks the worst-priority queued one displaces it
  (``pipeline_shed_total{reason="priority"}``, FIFO-safe for everything
  that survives) — established-flow batches are never stuck behind a
  flood. Rank comes from the producer's ``_prio`` column (the shim
  feeder's established/new/unknown classes); same-class traffic keeps
  plain FIFO admission. At OVERLOAD the full queue additionally rejects
  instantly instead of blocking producers.
- **Circuit breaker**: consecutive dispatch/finalize failures past
  ``breaker_threshold`` open the breaker — submissions fail fast with
  :class:`PipelineUnavailable` instead of burning the per-submission retry
  cap against a sick backend; after ``breaker_cooldown_s`` a half-open
  probe dispatch closes it again.
- **Watchdog-supervised restart**: worker heartbeats are armed around each
  blocking dispatch/finalize call; a beat armed past ``stall_timeout_s``
  (device stall) — or a worker crash — triggers the restart protocol: the
  wedged in-flight window is rejected, the stuck thread is abandoned
  behind a generation fence (it can never touch live state again), and a
  fresh worker starts on a fresh staging ring. Queued-but-uningested
  submissions survive a restart, preserving the FIFO/bit-identical
  contract for everything that still resolves. Restarts are bounded with
  capped backoff; past ``max_restarts`` the pipeline goes *hard-failed*
  and every submission is rejected fast.
- **State**: ``stats()["state"]`` ∈ ok / breaker-open / restarting /
  failed / closed folds into ``Engine.health()``, ``healthz`` and the
  ``pipeline_state`` gauge.

Fault injection: every dispatch fires the ``pipeline.dispatch`` point and
every finalize fires ``pipeline.finalize``. ``FaultInjected`` dispatch
trips are retried with a capped backoff (counted in
``pipeline_dispatch_faults_total``) until the breaker opens — an armed
chaos scenario delays batches but never loses or reorders them. Non-fault
dispatch errors reject only the affected tickets; the pipeline keeps
serving (supervised degradation, same philosophy as the engine's regen
path). The ``hang`` fault mode stalls cooperatively inside the point —
the scenario ``make chaos`` uses to force a watchdog restart.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.kernels.records import empty_batch, reset_batch_rows
from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.parallel.mesh import steer_rows
from cilium_tpu.pipeline.guard import (OVERLOAD_OVERLOAD, OVERLOAD_PRESSURE,
                                       PIPELINE_STATES, PRIO_NEW,
                                       CircuitBreaker, DeviceLost,
                                       PipelineClosed,
                                       PipelineDeadlineExceeded,
                                       PipelineDrop, PipelineError,
                                       PipelineTenantCap,
                                       PipelineUnavailable, Watchdog)
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.runtime.metrics import Metrics

log = logging.getLogger("cilium_tpu.pipeline")

#: retry caps for FaultInjected dispatch trips (the closing cap bounds
#: shutdown time when a fail-always fault is armed; the breaker usually
#: opens long before either cap is reached)
MAX_DISPATCH_RETRIES = 1000
MAX_DISPATCH_RETRIES_CLOSING = 25

#: backoff cap between watchdog restarts (seconds)
MAX_RESTART_BACKOFF_S = 5.0

#: the restart budget is a flap-stopper, not a lifetime kill switch: after
#: this long without a restart the spent budget is forgiven, so isolated
#: stalls weeks apart on a long-lived daemon never accumulate into a
#: hard-fail — only `max_restarts` restarts *within one window* do
RESTART_BUDGET_WINDOW_S = 300.0

#: the first dispatch of a worker generation may run a cold-shape XLA
#: compile inside dispatch_fn — give its heartbeat this multiple of the
#: stall timeout before the watchdog calls it a device stall, so a healthy
#: daemon's warmup can never restart-loop into hard-fail
COLD_DISPATCH_GRACE = 4

#: pre-binned ``_shard`` column encoding (written by the shim feeder, read
#: by the sharded staging ring): low bits carry shard+1 (0 = not binned),
#: high bits the policy revision the bin was hashed under — a bin from a
#: superseded revision is re-hashed at stage-write, because an LB-table
#: change moves service flows' post-DNAT steer hash (the same
#: harvest-vs-dispatch staleness class the dispatch-time ep-slot remap
#: exists for)
SHARD_BIN_SHIFT = 16
SHARD_BIN_MASK = (1 << SHARD_BIN_SHIFT) - 1
SHARD_BIN_REV_MASK = (1 << 31) - 1      # revision bits (int64 column)


def shard_bin_encode(shard: np.ndarray, revision: int) -> np.ndarray:
    """Producer-side ``_shard`` column encoding (int64): shard+1 in the
    low bits, the binning policy revision above — one definition shared
    with the feeder so writer and reader cannot drift."""
    return (np.int64((revision & SHARD_BIN_REV_MASK) << SHARD_BIN_SHIFT)
            | (shard.astype(np.int64) + 1))

# canonical out columns (the DatapathBackend.classify contract) — used to
# resolve all-invalid submissions without a device round trip
_OUT_SPEC: Tuple[Tuple[str, type, Tuple[int, ...]], ...] = (
    ("allow", bool, ()), ("reason", np.int32, ()), ("status", np.int32, ()),
    ("ct_full", bool, ()),
    ("remote_identity", np.int32, ()), ("redirect", bool, ()),
    ("svc", bool, ()), ("nat_dst", np.uint32, (4,)),
    ("nat_dport", np.int32, ()), ("rnat", bool, ()),
    ("rnat_src", np.uint32, (4,)), ("rnat_sport", np.int32, ()),
)


def _zero_out(n: int) -> Dict[str, np.ndarray]:
    return {k: np.zeros((n,) + shape, dtype=dt) for k, dt, shape in _OUT_SPEC}


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class _Superseded(BaseException):
    """Internal unwind signal: this worker's generation was replaced (the
    watchdog restarted the pipeline around it, or close() fenced it off).
    A BaseException so the supervised ``except Exception`` paths in the
    worker cannot swallow it; ``_run`` catches it and exits silently —
    the replacement already owns all state, nothing to clean up."""


class Ticket:
    """Handle for one submission. ``result()`` blocks until the pipeline
    resolved this submission's rows and returns the out dict (same row
    geometry as the submitted batch; invalid rows zero-filled, exactly like
    the serial classify path)."""

    __slots__ = ("seq", "n_rows", "n_valid", "submitted_mono", "trace_id",
                 "deadline_mono", "ingest_mono", "tenant", "_event", "_out",
                 "_exc")

    def __init__(self, n_rows: int, n_valid: int):
        self.seq = -1                      # assigned at admission
        self.n_rows = n_rows
        self.n_valid = n_valid
        self.trace_id = None               # observe/trace sampling decision
        # tenant NAME (QoS armed only; None otherwise) — rides the ticket
        # so sheds can carry a {tenant=} label without a table lookup
        self.tenant: Optional[str] = None
        self.submitted_mono = time.monotonic()
        # when the rows actually entered the host (the shim feeder's
        # harvest stamp, monotonic seconds) — what true ingest→verdict
        # latency is measured from; None for producers that submit the
        # instant they build the batch (submitted_mono is then the truth)
        self.ingest_mono: Optional[float] = None
        self.deadline_mono: Optional[float] = None   # shed-after fence
        self._event = threading.Event()
        self._out: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def dropped(self) -> bool:
        return isinstance(self._exc, PipelineDrop)

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"pipeline ticket seq={self.seq} not resolved "
                               f"within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._out

    # -- pipeline-internal ---------------------------------------------------
    def _resolve(self, out: Dict[str, np.ndarray]) -> None:
        self._out = out
        self._event.set()

    def _reject(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()


def _batch_prio(batch: Dict[str, np.ndarray]) -> int:
    """A submission's priority class: the BEST (minimum) ``_prio`` among
    its valid rows — one established-flow row is enough to outrank a
    flood batch, because shedding the batch would shed that flow with it.
    Producers without the column (control plane, tests) rank as new-flow
    traffic."""
    col = batch.get("_prio")
    if col is None:
        return PRIO_NEW
    p = np.asarray(col)[np.asarray(batch["valid"], dtype=bool)]
    return int(p.min()) if p.size else PRIO_NEW


def _batch_tenant(batch: Dict[str, np.ndarray]) -> int:
    """A submission's tenant: the DOMINANT ``_tenant`` id among its valid
    rows — a couple of stray rows must not reclassify a whole harvest
    batch onto another tenant's budget. Producers without the column
    (control plane, tests, QoS-off feeders) land on the default tenant."""
    col = batch.get("_tenant")
    if col is None:
        return 0
    t = np.asarray(col)[np.asarray(batch["valid"], dtype=bool)]
    if not t.size:
        return 0
    vals, counts = np.unique(t, return_counts=True)
    return int(vals[int(np.argmax(counts))])


class _Sub:
    """One admitted submission riding the queue. ``valid_idx`` is computed
    lazily on the worker — the direct-dispatch fast path never needs it."""

    __slots__ = ("ticket", "batch", "now", "prio", "tenant")

    def __init__(self, ticket: Ticket, batch: Dict[str, np.ndarray],
                 now: Optional[int], prio: int = PRIO_NEW, tenant: int = 0):
        self.ticket = ticket
        self.batch = batch
        self.now = now
        self.prio = prio
        self.tenant = tenant


class _Slice:
    """A submission's rows inside one dispatched bucket. ``valid_idx`` is
    None for a direct (zero-copy) dispatch: the out arrays already have the
    submission's row geometry. ``dst_rows`` (sharded staging only) lists
    the bucket rows this submission's valid rows were steered into, in
    submission order — gathering outputs through it at finalize IS the
    un-steer that keeps per-ticket verdicts in FIFO row order; unsharded
    staging packs rows contiguously from ``dst_start`` instead."""

    __slots__ = ("ticket", "valid_idx", "dst_start", "dst_rows")

    def __init__(self, ticket: Ticket, valid_idx: Optional[np.ndarray],
                 dst_start: int, dst_rows: Optional[np.ndarray] = None):
        self.ticket = ticket
        self.valid_idx = valid_idx
        self.dst_start = dst_start
        self.dst_rows = dst_rows


class _Inflight:
    __slots__ = ("finalize", "slices", "t_dispatch", "buf_idx")

    def __init__(self, finalize, slices, t_dispatch, buf_idx):
        self.finalize = finalize
        self.slices = slices
        self.t_dispatch = t_dispatch
        self.buf_idx = buf_idx


class _StageBuf:
    """One staging-ring slot: a preallocated column batch plus cached
    per-bucket prefix views, so a steady-state flush allocates nothing —
    neither columns nor the view dict handed to dispatch (the view dict
    for each power-of-two bucket is built once per buffer and reused; a
    buffer is never rewritten while its views are in flight, which is
    exactly the ring's recycle discipline).

    Sharded pipelines size the slot as ``n_shards`` per-shard segments
    (``rows = n_shards * seg_cap``): ingest scatters each valid row
    straight into its flow shard's segment, so the flushed view is already
    the steered layout the mesh wants. ``dirty`` tracks each segment's
    content high-water mark across reuses — flush restores empty-batch
    defaults only on [fill, dirty), not the whole tail, so segment resets
    stay proportional to actual traffic."""

    __slots__ = ("cols", "dirty", "_views")

    def __init__(self, rows: int, n_shards: int = 1):
        self.cols = empty_batch(rows)
        # shim-fed submissions carry raw endpoint ids so the dispatch-time
        # slot re-mapping survives coalescing; rows from producers without
        # the column stage as 0 (= "no raw id", left untouched downstream)
        self.cols["_ep_raw"] = np.zeros((rows,), dtype=np.int64)
        self.dirty: Optional[List[int]] = [0] * n_shards \
            if n_shards > 1 else None
        self._views: Dict[int, Dict[str, np.ndarray]] = {}

    def view(self, bucket: int) -> Dict[str, np.ndarray]:
        v = self._views.get(bucket)
        if v is None:
            v = {k: col[:bucket] for k, col in self.cols.items()}
            self._views[bucket] = v
        return v


class Pipeline:
    """The scheduler. ``dispatch_fn(batch, now)`` must enqueue one batch and
    return a zero-arg finalize callable yielding the out dict — the Engine
    provides a closure over ``DatapathBackend.classify_async`` that also
    feeds metrics and the flow log.

    Producers call :meth:`submit` from any thread; one worker thread owns
    staging, dispatch, and finalization, which is what guarantees CT-order
    == submission-order. A watchdog thread supervises the worker (see the
    module docstring's guard-layer section)."""

    def __init__(self, dispatch_fn: Callable, *,
                 metrics: Optional[Metrics] = None,
                 max_bucket: int = 8192, min_bucket: int = 256,
                 queue_batches: int = 64, admission: str = "block",
                 block_timeout_s: float = 1.0, flush_ms: float = 2.0,
                 inflight: int = 2, name: str = "pipeline",
                 tracer: Optional[Tracer] = None,
                 deadline_ms: float = 0.0,
                 breaker_threshold: int = 20,
                 breaker_cooldown_s: float = 5.0,
                 stall_timeout_s: float = 30.0,
                 max_restarts: int = 3,
                 restart_backoff_s: float = 0.2,
                 n_shards: int = 1,
                 shard_fn: Optional[Callable] = None,
                 shard_headroom: int = 4,
                 shard_rev_fn: Optional[Callable[[], int]] = None,
                 mesh_shards: int = 0,
                 rss_mode: str = "host",
                 event_sink: Optional[Callable] = None,
                 qos=None,
                 lane_bucket: int = 0,
                 on_device_loss: Optional[Callable] = None):
        if max_bucket & (max_bucket - 1) or max_bucket <= 0:
            raise ValueError("max_bucket must be a power of two")
        if min_bucket & (min_bucket - 1) or not 0 < min_bucket <= max_bucket:
            raise ValueError("min_bucket must be a power of two "
                             "<= max_bucket")
        if lane_bucket and (lane_bucket & (lane_bucket - 1)
                            or not 0 < lane_bucket <= max_bucket):
            raise ValueError("lane_bucket must be 0 (lane off) or a power "
                             "of two <= max_bucket")
        if admission not in ("block", "drop"):
            raise ValueError(f"bad admission mode {admission!r}")
        if inflight < 1 or queue_batches < 1:
            raise ValueError("inflight and queue_batches must be >= 1")
        if deadline_ms < 0:
            raise ValueError("deadline_ms must be >= 0 (0 = no deadline)")
        if max_restarts < 0 or restart_backoff_s <= 0:
            raise ValueError("max_restarts must be >= 0 and "
                             "restart_backoff_s > 0")
        if n_shards < 1:
            # any positive count is a valid geometry: flow steering is
            # modulo (parallel/mesh.flow_shard_of), and a fenced re-mesh
            # leaves the serving set at n-1 survivors — a pipeline built
            # lazily (or restarted) against a degraded datapath must come
            # up at that same non-pow2 width remesh() would have adopted
            raise ValueError("n_shards must be >= 1")
        if shard_headroom < 1 or shard_headroom & (shard_headroom - 1):
            raise ValueError("shard_headroom must be a power of two >= 1")
        if n_shards > 1 and shard_fn is None:
            raise ValueError("a sharded pipeline needs shard_fn "
                             "(per-row flow-shard ids)")
        if rss_mode not in ("host", "device"):
            raise ValueError(f"bad rss_mode {rss_mode!r} (host | device)")
        if rss_mode == "device" and n_shards > 1:
            # device RSS deletes host steering by definition: steered
            # (per-shard-segment) staging under it would reintroduce the
            # very scatter the ppermute exchange retires
            raise ValueError("rss_mode='device' stages unsharded "
                             "(n_shards must be 1; pass the mesh size via "
                             "mesh_shards)")
        self._dispatch_fn = dispatch_fn
        # the serving mesh behind this pipeline, for the per-mesh guard
        # surface: with device-side RSS the staging ring is UNSHARDED
        # (n_shards == 1 — row order carries no placement semantics) but
        # one watchdog/breaker generation still fences mesh_shards chips
        self._mesh_shards = mesh_shards if mesh_shards > 0 else n_shards
        self._rss_mode = rss_mode
        # sharded staging (the software-RSS half of the multi-chip path):
        # each staging slot holds n_shards per-shard segments of seg_cap
        # rows; ingest steers rows into their segment, flush dispatches the
        # ONE steered shape [n_shards * seg_cap] every time (a single XLA
        # trace per wire format — sharded serving trades padded transfer
        # bytes for zero recompile storms, exactly like the bench's
        # uniform per-shard sizing). seg_cap carries `shard_headroom`x the
        # even-split share so hash skew doesn't force tiny aggregates; a
        # submission more skewed than that is shed ("steer_overflow"),
        # never a worker-killing error.
        self._n_shards = n_shards
        self._shard_fn = shard_fn
        self._shard_rev_fn = shard_rev_fn
        # kept as an attr (unlike the other ctor-only sizing inputs):
        # remesh() recomputes seg_cap/stage_rows for the survivor count
        self._shard_headroom = shard_headroom
        # mesh self-healing (ISSUE 19): a DeviceLost dispatch parks this
        # worker (queue survives) and notifies the engine via the callback;
        # Pipeline.remesh() is the fenced geometry swap that un-parks. With
        # no handler wired (bare pipelines, tests) DeviceLost degrades to
        # the generic dispatch-error path — behavior identical to pre-19.
        self._on_device_loss = on_device_loss
        self._device_lost: Optional[int] = None
        # a freshly restarted/re-meshed generation proves the device path
        # with a 1-row synthetic dispatch before serving real traffic
        self._canary_pending = False
        if n_shards > 1:
            self._seg_cap = min(max_bucket, _next_pow2(
                max(1, max_bucket // n_shards) * shard_headroom))
            self._stage_rows = n_shards * self._seg_cap
        else:
            self._seg_cap = 0
            self._stage_rows = max_bucket
        self._shard_fill: List[int] = [0] * n_shards
        # lifetime per-shard ingest totals: the steering-balance surface
        # (bench schema checks + operators read skew from here)
        self._shard_rows_total: List[int] = [0] * n_shards
        # the policy revision the staged bucket was steered under (-2 =
        # riders steered under different revisions): rides into
        # dispatch_fn so the engine can detect a regen landing between
        # stage-write and dispatch and have the datapath RE-steer under
        # the snapshot it actually classifies with — an LB change moves
        # service flows' post-DNAT hash, and dispatching a stale steer
        # would strand their CT entries on the wrong shard
        self._stage_steer_rev: Optional[int] = None
        self._shard_gauge_names = [
            f'pipeline_staged_rows{{shard="{s}"}}'
            for s in range(n_shards)] if n_shards > 1 else []
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else TRACER
        # guard-event sink (the flight recorder, observe/blackbox.py):
        # breaker transitions, watchdog restarts and sheds are narrated to
        # it so an anomaly freezes with its lead-up intact. Fired outside
        # the pipeline lock, exceptions swallowed — a broken recorder can
        # never take the worker down
        self._event_sink = event_sink
        self._max_bucket = max_bucket
        self._min_bucket = min_bucket
        self._queue_max = queue_batches
        self._admission = admission
        self._block_timeout_s = block_timeout_s
        self._flush_s = flush_ms / 1e3
        self._inflight_max = inflight
        self._default_deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self._name = name

        # overload-ladder level (pipeline/guard.OverloadLadder, propagated
        # by the engine's overload controller; plain-int writes are atomic
        # under the GIL). >= PRESSURE arms priority shedding at admission;
        # >= OVERLOAD additionally fails admission fast (no blocking waits
        # — a saturated queue under overload must push backpressure to the
        # producer immediately, not park its threads)
        self._overload_level = 0

        # multi-tenant QoS (cilium_tpu/qos): when a TenantTable is passed
        # the admission queue becomes per-tenant weighted-fair (DRR); with
        # qos=None the queue is the plain FIFO deque — byte-identical to
        # the pre-QoS pipeline, which is what keeps the default-off
        # contract trivially true
        self._qos = qos
        self._lane_bucket = lane_bucket if qos is not None else 0

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        if qos is not None:
            from cilium_tpu.qos.wfq import TenantQueues
            self._queue = TenantQueues(qos, quantum_rows=max_bucket,
                                       lane_rows=self._lane_bucket)
        else:
            self._queue = deque()
        self._outstanding = 0            # accepted tickets not yet resolved
        self._drain_req = 0
        self._closing = False
        self._closed = False
        self._next_seq = 0

        # guard state (generation fence + restart budget)
        self._gen = 0                    # current worker generation
        self._worker_gen = 0             # generation self._worker runs
        self._restarts = 0
        self._last_restart_mono = 0.0
        self._max_restarts = max_restarts
        self._restart_backoff_s = restart_backoff_s
        self._restarting = False
        self._failed = False             # hard-failed: restart budget spent
        self._cold_dispatch = True       # this gen has not dispatched yet
        #: armed heartbeat: (armed_mono, label, gen, stall multiplier)
        self._hb: Optional[Tuple[float, str, int, int]] = None

        # worker-owned (no lock): staging ring + inflight window
        self._buffers = [_StageBuf(self._stage_rows, n_shards)
                         for _ in range(inflight + 1)]
        self._free_bufs: List[int] = list(range(len(self._buffers)))
        self._stage_buf: Optional[int] = None
        self._staged_rows = 0
        self._staged_slices: List[_Slice] = []
        self._stage_deadline = 0.0
        self._stage_now: Optional[int] = None
        self._inflight: deque = deque()
        self._current: Optional[_Sub] = None   # popped, mid-_ingest
        self._dispatching: List[_Slice] = []   # handed to _dispatch, not
        self._finalizing: Optional[_Inflight] = None   # ... yet inflight

        # stats. submitted/admission_drops/shed mutate under self._lock;
        # the worker-owned counters are mirrored into the _pub snapshot
        # (also under the lock) so stats() never does a cross-thread
        # unsynchronized read of in-flux worker state
        self.submitted = 0
        self.admission_drops = 0
        self.dispatched_batches = 0
        self.completed_batches = 0
        self.dispatch_faults = 0
        self.dispatch_errors = 0
        self.shed_total = 0
        self.shed_reasons: Dict[str, int] = {}
        self.unavailable_total = 0
        self.flush_reasons: Dict[str, int] = {
            "direct": 0, "full": 0, "deadline": 0, "drain": 0, "lane": 0}
        self._fill_rows = 0
        self._bucket_rows = 0
        # latency-lane fill accounting (reason="lane" dispatches only) —
        # the autotuner's lane/bulk arbitration signal
        self._lane_fill_rows = 0
        self._lane_bucket_rows = 0
        self._pub: Dict = {}             # worker-published stats snapshot

        if self._mesh_shards > 1:
            # the guard runs per-mesh: one breaker/watchdog generation
            # fences ALL shards together (a wedged shard must never yield
            # half-mesh verdicts), and the gauge says how many chips one
            # restart takes down — true for steered AND device-RSS meshes
            # (device mode stages unsharded but one dispatch still covers
            # every chip)
            self.metrics.set_gauge("pipeline_mesh_shards",
                                   self._mesh_shards)
            self._hb_dispatch_label = f"dispatch[mesh={self._mesh_shards}]"
            self._hb_finalize_label = f"finalize[mesh={self._mesh_shards}]"
        else:
            self._hb_dispatch_label = "dispatch"
            self._hb_finalize_label = "finalize"
        self.breaker = CircuitBreaker(
            breaker_threshold, breaker_cooldown_s, metrics=self.metrics,
            tracer=self.tracer, name=name,
            on_transition=self._on_breaker_transition)
        self._watchdog = Watchdog(
            stall_timeout_s=stall_timeout_s,
            heartbeat=lambda: self._hb,
            on_stall=self._restart_worker,
            should_stop=lambda: self._closed or self._failed,
            name=name)

        self._worker = threading.Thread(target=self._run, args=(0,),
                                        daemon=True, name=f"{name}-worker")
        self._worker.start()
        self._watchdog.start()

    # -- producer side -------------------------------------------------------
    def submit(self, batch: Dict[str, np.ndarray],
               now: Optional[int] = None,
               timeout: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               ingest_mono: Optional[float] = None) -> Ticket:
        """Admit one batch (records layout, ``valid``-masked). Returns a
        :class:`Ticket` immediately; with ``admission="drop"`` (or a blocked
        admission that times out) the ticket comes back already rejected
        with :class:`PipelineDrop` — check ``ticket.dropped``.

        ``deadline_ms`` (default: the pipeline-wide ``deadline_ms``, 0 =
        none) bounds how stale this submission may get: work the worker
        cannot reach/dispatch before the deadline is shed with
        :class:`PipelineDeadlineExceeded` instead of burning device time.
        Raises :class:`PipelineUnavailable` (fail fast, no queueing) while
        the circuit breaker is open or the pipeline is hard-failed.

        The caller must not mutate ``batch`` until the ticket resolves (the
        staging copy happens on the worker; a direct-dispatch batch is read
        by the flow log at finalize time)."""
        valid = np.asarray(batch["valid"])
        n_valid = int(valid.sum())
        if n_valid > self._max_bucket:
            raise ValueError(
                f"submission has {n_valid} valid rows > max_bucket "
                f"{self._max_bucket}; split it or raise batch_size")
        if self._failed:
            self._count_unavailable()
            raise PipelineUnavailable(
                f"pipeline hard-failed after {self._restarts} worker "
                "restarts; no new submissions")
        if not self.breaker.admit():
            self._count_unavailable()
            raise PipelineUnavailable(
                "circuit breaker open after consecutive dispatch failures; "
                f"retry in {self.breaker.stats().get('retry_in_s', 0.0)}s")
        ticket = Ticket(n_rows=int(valid.shape[0]), n_valid=n_valid)
        # the harvest stamp rides the ticket so verdict-apply can compute
        # TRUE ingest→verdict latency (queue wait alone measures only the
        # pipeline's share of the 30-60x compute-vs-end-to-end gap)
        ticket.ingest_mono = ingest_mono
        dl = self._default_deadline_s if deadline_ms is None \
            else (deadline_ms / 1e3 if deadline_ms > 0 else None)
        if dl is not None:
            ticket.deadline_mono = ticket.submitted_mono + dl
        # the sampling decision is made once per submission and rides the
        # ticket; unsampled submissions pay exactly one counter draw here
        ticket.trace_id = self.tracer.maybe_sample()
        deadline = time.monotonic() + (
            self._block_timeout_s if timeout is None else timeout)
        prio = _batch_prio(batch)
        tenant = 0
        if self._qos is not None:
            # classify-time tenant derivation is a guarded shed path
            # (fault point "qos.enqueue"): if it faults, the ticket fails
            # CLOSED onto the default-tenant FIFO class — served, just
            # without a private budget — and the producer thread survives
            try:
                FAULTS.fire("qos.enqueue")
                tenant = _batch_tenant(batch)
            except FaultInjected:
                self.metrics.inc_counter("qos_enqueue_failsafe_total")
                tenant = 0
            ticket.tenant = self._qos.name_of(tenant)
        victim: Optional[_Sub] = None
        try:
            with self._lock:
                if self._closing or self._closed:
                    raise PipelineClosed("pipeline is closed")
                if self._failed:
                    # re-check under the lock: a hard-fail landing between
                    # the unlocked check above and here must not enqueue a
                    # ticket nothing will ever serve
                    self._count_unavailable_locked()
                    raise PipelineUnavailable(
                        f"pipeline hard-failed after {self._restarts} "
                        "worker restarts; no new submissions")
                qs = self._queue if self._qos is not None else None
                while True:
                    qfull = len(self._queue) >= self._queue_max
                    # per-tenant occupancy cap (QoS only): the tenant is
                    # at its OWN budget even if the shared queue has room
                    # — it waits/sheds against that budget, never spending
                    # the other tenants' headroom
                    tcap = qs is not None and qs.over_cap(tenant)
                    if not qfull and not tcap:
                        break
                    if qfull and not tcap and victim is None \
                            and self._overload_level >= OVERLOAD_PRESSURE:
                        # priority shedding (the degradation ladder's
                        # PRESSURE behavior): a full queue sheds its
                        # WORST-ranked submission in favor of a
                        # better-ranked newcomer — established-flow
                        # batches displace flood batches instead of
                        # queueing behind them. Same-class traffic keeps
                        # the plain FIFO admission below. With QoS armed
                        # the scan is tenant-scoped: the worst-PRESSURE
                        # tenant (queue depth over weight) sheds first,
                        # and within the submitter's own tenant the old
                        # strictly-worse-class contract still holds. The
                        # scan is gated on `not tcap`: a submitter at its
                        # own cap gains nothing from displacing someone
                        # else, so no victim is removed it cannot use —
                        # and once one IS removed we break unconditionally
                        # (the lock is held throughout, so the just-
                        # checked cap cannot have changed) straight to
                        # the enqueue below: no loop exit can strand an
                        # already-removed victim.
                        victim = (self._queue.priority_victim(prio, tenant)
                                  if qs is not None
                                  else self._priority_victim_locked(prio))
                        if victim is not None:
                            self._queue.remove(victim)
                            self.metrics.set_gauge("pipeline_queue_depth",
                                                   len(self._queue))
                            break
                    remaining = deadline - time.monotonic()
                    # OVERLOAD fail-fast is tenant-scoped under QoS: only
                    # a tenant at-or-over its weight share of the queue is
                    # instant-rejected; a within-budget tenant still gets
                    # the blocking wait (its backlog is someone else's
                    # flood)
                    fail_fast = self._overload_level >= OVERLOAD_OVERLOAD \
                        and (qs is None or qs.over_share(tenant))
                    if self._admission == "drop" or remaining <= 0 \
                            or fail_fast:
                        if tcap and not qfull:
                            # the tenant's own cap is the binding
                            # constraint: this is a shed against its
                            # private budget, not a shared-queue
                            # admission drop
                            self.shed_total += 1
                            self.shed_reasons["tenant_cap"] = \
                                self.shed_reasons.get("tenant_cap", 0) + 1
                            self.metrics.inc_counter(
                                'pipeline_shed_total'
                                '{reason="tenant_cap"}')
                            self.metrics.inc_counter(
                                f'pipeline_shed_total{{reason="tenant_cap"'
                                f',tenant="{ticket.tenant}"}}')
                            ticket._reject(PipelineTenantCap(
                                f"tenant {ticket.tenant!r} at its "
                                f"occupancy cap "
                                f"({qs.table.cap_of(tenant)} batches); "
                                f"admission={self._admission}"))
                            return ticket
                        self.admission_drops += 1
                        # the unlabeled family counts EVERY drop — QoS on
                        # or off — so pre-QoS dashboards/alerts keep
                        # working when QoS is armed; the tenant-labeled
                        # family rides alongside it (the shard-metrics
                        # discipline), never instead of it
                        self.metrics.inc_counter(
                            "pipeline_admission_drops_total")
                        if ticket.tenant is not None:
                            self.metrics.inc_counter(
                                f'pipeline_admission_drops_total'
                                f'{{tenant="{ticket.tenant}"}}')
                        ticket._reject(PipelineDrop(
                            f"queue full ({self._queue_max} batches); "
                            f"admission={self._admission}"
                            + (", overload fail-fast"
                               if fail_fast else "")))
                        return ticket
                    self._cond.wait(min(remaining, 0.05))
                    if self._closing or self._closed:
                        raise PipelineClosed("pipeline closed while "
                                             "blocked at admission")
                    if self._failed:
                        # hard-fail swept the queue out from under us; the
                        # freed capacity must not admit work nothing will
                        # serve
                        self._count_unavailable_locked()
                        raise PipelineUnavailable(
                            "pipeline hard-failed while blocked at "
                            "admission")
                ticket.seq = self._next_seq
                self._next_seq += 1
                self._queue.append(_Sub(ticket, batch, now, prio=prio,
                                        tenant=tenant))
                self.submitted += 1
                self._outstanding += 1
                self.metrics.set_gauge("pipeline_queue_depth",
                                       len(self._queue))
                self._cond.notify_all()
        finally:
            if victim is not None:
                # settle OUTSIDE the lock (_shed takes it; the `with`
                # block has exited by the time `finally` runs). A removed
                # victim settles on EVERY exit path — the normal enqueue,
                # the reject returns, and the closed/hard-fail raises —
                # or its producer would block forever on a ticket nothing
                # owns and _outstanding would never drain. A racing sweep
                # dedupes through ticket.done().
                self._shed(victim.ticket, "priority", PipelineDrop(
                    f"priority shed: displaced by a class-{prio} "
                    f"submission under overload state "
                    f"{self._overload_level} "
                    f"(seq={victim.ticket.seq}, class={victim.prio})"))
        return ticket

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted submission so far has resolved
        (flushes any staged microbatch immediately — ``drain`` flush
        reason). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            self._drain_req += 1
            self._cond.notify_all()
            try:
                while self._outstanding > 0:
                    remaining = None if deadline is None else \
                        deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._cond.wait(remaining if remaining is None
                                    else min(remaining, 0.1))
            finally:
                self._drain_req -= 1
                self._cond.notify_all()
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        """Clean shutdown: stop admitting, process everything already
        queued/staged/in flight, then stop the worker. If the worker does
        not stop within ``timeout`` (wedged in a device call) it is fenced
        off behind a generation bump and every outstanding ticket is
        swept and rejected — close() never strands a waiter. Idempotent."""
        with self._lock:
            if self._closed and not self._worker.is_alive():
                return
            self._closing = True
            self._cond.notify_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if self._closed:
                    break       # the watchdog's shutdown sweep beat us
                if self._failed or self._worker_gen != self._gen:
                    # the current worker object is fenced (hard-fail, or a
                    # restart aborted mid-backoff): it will never drain —
                    # stop waiting and let the sweep below settle leftovers
                    break
                worker = self._worker
            # lap-join, never an unbounded join: a worker wedged in a
            # device call would otherwise block close(timeout=None)
            # forever — the watchdog fences it at stall_timeout and sets
            # _closed, which the lap re-check above observes
            remaining = None if deadline is None else \
                max(0.0, deadline - time.monotonic())
            worker.join(0.2 if remaining is None else min(0.2, remaining))
            with self._lock:
                if not worker.is_alive() and worker is self._worker:
                    break       # clean exit, no restart swapped it
            if deadline is not None and time.monotonic() >= deadline:
                break           # out of budget; sweep below
        stranded: List[Ticket] = []
        with self._lock:
            self._closed = True
            wedged = self._worker.is_alive()
            if wedged or self._outstanding > 0:
                # the worker is stuck in a device call (or a restart was
                # aborted mid-backoff with work still queued): fence it off
                # and sweep — a fenced worker that later wakes sees a stale
                # generation and exits without touching live state
                self._gen += 1
                stranded = self._collect_wedged_locked(include_queue=True)
            self._cond.notify_all()
        if stranded:
            log.warning(
                "pipeline close: worker %s; rejecting %d outstanding "
                "ticket(s)", "did not stop within timeout" if wedged
                else "already gone with work queued", len(stranded))
            self._settle([(t, None, PipelineError(
                "pipeline closed before this submission resolved"))
                for t in stranded])
        # departed-subject gauge sweep (ISSUE 13): a closed pipeline's
        # per-shard staged-rows series would otherwise export their last
        # fills forever — and after a mesh resize (engine restarted onto a
        # different shard count) the old shard labels would pin a gauge no
        # live structure backs. Same drop_gauge sweep departed clustermesh
        # peers and deregistered ledger resources get.
        for name in self._shard_gauge_names:
            self.metrics.drop_gauge(name)

    # -- runtime-tunable knobs (observe/autotune.py + chaos consumers) --------
    @property
    def flush_ms(self) -> float:
        return self._flush_s * 1e3

    @property
    def min_bucket(self) -> int:
        return self._min_bucket

    @property
    def max_bucket(self) -> int:
        return self._max_bucket

    @property
    def stall_timeout_s(self) -> float:
        return self._watchdog.stall_timeout_s

    def set_flush_ms(self, flush_ms: float) -> None:
        """Retarget the microbatch coalesce deadline (applies to the next
        staged submission; an already-armed deadline keeps its anchor)."""
        if flush_ms <= 0:
            raise ValueError("flush_ms must be > 0")
        with self._lock:
            self._flush_s = flush_ms / 1e3
            self._cond.notify_all()     # re-evaluate a parked deadline wait

    def set_min_bucket(self, min_bucket: int) -> None:
        """Move the smallest dispatch shape (the bucket-set floor)."""
        if min_bucket & (min_bucket - 1) or \
                not 0 < min_bucket <= self._max_bucket:
            raise ValueError("min_bucket must be a power of two "
                             "<= max_bucket")
        with self._lock:
            self._min_bucket = min_bucket

    @property
    def lane_bucket(self) -> int:
        return self._lane_bucket

    def set_lane_bucket(self, lane_bucket: int) -> None:
        """Move the latency lane's dispatch shape (the always-armed small
        bucket lane-tenant submissions flush at). 0 disarms the lane;
        the autotuner arbitrates it within [its floor, min_bucket]."""
        if lane_bucket and (lane_bucket & (lane_bucket - 1)
                            or not 0 < lane_bucket <= self._max_bucket):
            raise ValueError("lane_bucket must be 0 or a power of two "
                             "<= max_bucket")
        with self._lock:
            self._lane_bucket = lane_bucket if self._qos is not None else 0
            if self._qos is not None:
                # keep the DRR's lane-bypass threshold in lockstep with
                # the lane's dispatch shape
                self._queue.lane_rows = self._lane_bucket

    def set_stall_timeout_s(self, stall_timeout_s: float) -> None:
        """Retarget the watchdog's stall budget (e.g. widen it before a
        cold dispatch that will JIT-compile, shrink it in chaos drills)."""
        if stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be > 0")
        self._watchdog.stall_timeout_s = stall_timeout_s

    # -- introspection --------------------------------------------------------
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._failed:
            return "failed"
        if self._closed or self._closing:
            return "closed"
        if self._restarting:
            return "restarting"
        if self._device_lost is not None:
            return "device-lost"
        if self.breaker.state != "closed":
            return "breaker-open"
        return "ok"

    def occupancy_stats(self) -> Dict:
        """The bounded-structure subset of :meth:`stats` for the resource
        ledger's per-poll sweep — no histogram quantile math, one lock
        acquisition (the <2% ledger-polling attestation is the budget)."""
        with self._lock:
            pub = self._pub
            return {
                "queue_depth": len(self._queue),
                "queue_max": self._queue_max,
                "n_shards": self._n_shards,
                "mesh_shards": self._mesh_shards,
                "rss_mode": self._rss_mode,
                # aggregate staging rows: n_shards * seg_cap when sharded
                # (seg_cap carries headroom, so this exceeds max_bucket)
                "stage_rows": self._stage_rows,
                "shard_capacity": self._seg_cap,
                "shard_fill": list(pub.get("shard_fill",
                                           [0] * self._n_shards)),
                "staged_rows": pub.get("staged_rows", 0),
                "staging_free": pub.get("staging_free",
                                        self._inflight_max + 1),
                "staging_slots": pub.get("staging_slots",
                                         self._inflight_max + 1),
                # active per-tenant queue occupancy (QoS armed only):
                # {name: (cap_batches, queued_batches)} for the ledger's
                # qos_tenant_queue_* rows
                **({"tenants": self._queue.occupancy_by_name()}
                   if self._qos is not None else {}),
            }

    def stats(self) -> Dict:
        with self._lock:
            queue_depth = len(self._queue)
            outstanding = self._outstanding
            pub = dict(self._pub)
            state = self._state_locked()
            restarts = self._restarts
            submitted = self.submitted
            admission_drops = self.admission_drops
            shed_total = self.shed_total
            shed_reasons = dict(self.shed_reasons)
            unavailable = self.unavailable_total
            tenants = (self._queue.stats() if self._qos is not None
                       else None)
        qw = self.metrics.histograms.get("pipeline_queue_wait_seconds")
        flush_reasons = pub.get("flush_reasons") or dict(self.flush_reasons)
        fill_rows = pub.get("fill_rows", 0)
        bucket_rows = pub.get("bucket_rows", 0)
        return {
            "state": state,
            "submitted": submitted,
            "outstanding": outstanding,
            "queue_depth": queue_depth,
            "queue_max": self._queue_max,
            "overload_level": self._overload_level,
            "n_shards": self._n_shards,
            # the mesh behind this pipeline + where RSS runs: with
            # rss_mode="device" n_shards is 1 (unsharded staging) while
            # mesh_shards still names the chips one guard fence covers
            "mesh_shards": self._mesh_shards,
            "rss_mode": self._rss_mode,
            **({"shard_capacity": self._seg_cap,
                "shard_fill": pub.get("shard_fill",
                                      [0] * self._n_shards),
                "shard_rows_total": pub.get("shard_rows_total",
                                            [0] * self._n_shards)}
               if self._n_shards > 1 else {}),
            "staged_rows": pub.get("staged_rows", 0),
            "inflight": pub.get("inflight", 0),
            "staging_free": pub.get("staging_free",
                                    self._inflight_max + 1),
            "staging_slots": pub.get("staging_slots",
                                     self._inflight_max + 1),
            "admission_drops": admission_drops,
            "dispatched_batches": pub.get("dispatched_batches",
                                          self.dispatched_batches),
            "completed_batches": pub.get("completed_batches",
                                         self.completed_batches),
            # monotone ints bumped mid-retry-loop: the attr is always
            # current, the published snapshot only moves on batch
            # boundaries — read the live value
            "dispatch_faults": self.dispatch_faults,
            "dispatch_errors": self.dispatch_errors,
            "flush_reasons": flush_reasons,
            "fill_rows": fill_rows,
            "bucket_rows": bucket_rows,
            "shed_total": shed_total,
            "shed_reasons": shed_reasons,
            "unavailable_total": unavailable,
            "restarts": restarts,
            "max_restarts": self._max_restarts,
            "stall_timeout_s": self._watchdog.stall_timeout_s,
            "breaker": self.breaker.stats(),
            "flush_ms": self.flush_ms,
            "min_bucket": self._min_bucket,
            # multi-tenant QoS surface (absent when QoS is off, so the
            # QoS-off stats doc is byte-identical to the pre-QoS one)
            **({"tenants": tenants,
                "lane_bucket": self._lane_bucket,
                "lane_fill_rows": pub.get("lane_fill_rows", 0),
                "lane_bucket_rows": pub.get("lane_bucket_rows", 0)}
               if tenants is not None else {}),
            "fill_ratio_avg": round(fill_rows / max(1, bucket_rows), 4),
            "queue_wait_p50_ms": round(qw.quantile(0.5) * 1e3, 3)
            if qw else 0.0,
            "queue_wait_p99_ms": round(qw.quantile(0.99) * 1e3, 3)
            if qw else 0.0,
            "closed": self._closed or self._closing,
        }

    # -- guard plumbing -------------------------------------------------------
    def set_overload_state(self, level: int) -> None:
        """Propagate the overload-ladder level (engine's overload
        controller). Level semantics live in pipeline/guard.py."""
        self._overload_level = int(level)
        with self._lock:
            self._cond.notify_all()   # blocked producers re-evaluate

    def _priority_victim_locked(self, incoming_prio: int) -> Optional[_Sub]:
        """Lock held: the queued submission a better-ranked newcomer may
        displace — the worst priority class in the queue, newest first
        (shedding the freshest flood batch preserves the most FIFO
        history). None when nothing ranks strictly worse than the
        newcomer."""
        worst: Optional[_Sub] = None
        for sub in self._queue:
            if worst is None or sub.prio >= worst.prio:
                worst = sub
        if worst is not None and worst.prio > incoming_prio:
            return worst
        return None

    def _count_unavailable(self) -> None:
        with self._lock:
            self._count_unavailable_locked()

    def _count_unavailable_locked(self) -> None:
        self.unavailable_total += 1
        self.metrics.inc_counter("pipeline_unavailable_total")

    def _emit(self, kind: str, **attrs) -> None:
        sink = self._event_sink
        if sink is None:
            return
        try:
            sink(kind, **attrs)
        except Exception:   # noqa: BLE001 — the sink is observability-only
            log.exception("pipeline event sink failed for %r", kind)

    def _on_breaker_transition(self, old: str, new: str) -> None:
        self._set_state_gauge()
        self._emit("breaker", old=old, new=new)

    def _set_state_gauge(self) -> None:
        self.metrics.set_gauge("pipeline_state",
                               PIPELINE_STATES.get(self.state(), -1))

    def _hb_arm(self, label: str, gen: int, grace: int = 1) -> None:
        # tuple assignment is atomic under the GIL; the watchdog reads it
        self._hb = (time.monotonic(), label, gen, grace)

    def _hb_clear(self, gen: int) -> None:
        # gen-checked: a fenced-off worker waking from a stall must not
        # clear the REPLACEMENT worker's armed heartbeat
        hb = self._hb
        if hb is not None and hb[2] == gen:
            self._hb = None

    def _stale(self, gen: int) -> bool:
        return self._gen != gen

    def _check_gen(self, gen: int) -> None:
        """Raise the unwind signal when this worker has been superseded.
        Called after every return from a blocking call — a fenced-off
        worker must never touch live scheduler state again."""
        if self._gen != gen:
            raise _Superseded()

    def _settle(self, outcomes) -> None:
        """The single resolution path: ``outcomes`` is a sequence of
        ``(ticket, out_or_None, exc_or_None)``. Settles each not-yet-done
        ticket and adjusts ``_outstanding`` for exactly the tickets that
        transitioned — under the lock, so a watchdog sweep racing a waking
        worker can never double-resolve or double-count."""
        with self._lock:
            n = 0
            for ticket, out, exc in outcomes:
                if ticket.done():
                    continue
                if exc is not None:
                    ticket._reject(exc)
                else:
                    ticket._resolve(out)
                n += 1
            self._outstanding -= n
            # drain waiters only care about reaching zero; producers are
            # woken by the queue pop — skip the per-batch thundering herd
            if self._outstanding == 0 or self._closing:
                self._cond.notify_all()

    def _collect_wedged_locked(self, include_queue: bool) -> List[Ticket]:
        """Lock held. Gather every ticket the (dead/wedged) worker owned —
        mid-ingest sub, staged slices, a dispatch/finalize in progress, the
        whole in-flight window, optionally the queue — and reset the
        worker-owned state to a fresh staging ring."""
        # read registries in DATA-FLOW order (current -> staged ->
        # dispatching -> inflight -> finalizing): every worker hand-off
        # adds to the destination before removing from the source, so a
        # ticket mid-hand-off is seen in the source, the destination, or
        # both — never in neither. (queue->_current happens under this
        # lock, so reading the queue last is safe.)
        wedged: List[Ticket] = []
        if self._current is not None:
            wedged.append(self._current.ticket)
            self._current = None
        wedged.extend(sl.ticket for sl in self._staged_slices)
        wedged.extend(sl.ticket for sl in self._dispatching)
        for inf in self._inflight:
            wedged.extend(sl.ticket for sl in inf.slices)
        if self._finalizing is not None:
            wedged.extend(sl.ticket for sl in self._finalizing.slices)
        if include_queue:
            wedged.extend(s.ticket for s in self._queue)
            self._queue.clear()
            self.metrics.set_gauge("pipeline_queue_depth", 0)
        # fresh staging ring: the old buffers may still be referenced by
        # the fenced-off worker — never reuse them
        self._buffers = [_StageBuf(self._stage_rows, self._n_shards)
                         for _ in range(self._inflight_max + 1)]
        self._free_bufs = list(range(len(self._buffers)))
        self._shard_fill = [0] * self._n_shards
        # the gauge is otherwise only touched in acquire/recycle: without
        # this it would report the wedged worker's last value (usually 0)
        # through the whole recovery window
        self.metrics.set_gauge("pipeline_staging_free",
                               len(self._free_bufs))
        for name in self._shard_gauge_names:
            self.metrics.set_gauge(name, 0)   # fresh ring: empty segments
        self._stage_buf = None
        self._staged_rows = 0
        self._staged_slices = []
        self._stage_now = None
        self._dispatching = []
        self._finalizing = None
        self._inflight = deque()
        self._hb = None
        self._pub = {}
        return wedged

    def _restart_worker(self, gen: int, reason: str) -> None:
        """The restart protocol (watchdog thread, or the dying worker
        itself on a crash). Generation-fenced: a stale ``gen`` is a no-op,
        so a watchdog firing while a crash restart is already underway
        cannot double-restart."""
        with self._lock:
            if gen != self._gen or self._closed or self._failed:
                return
            if self._closing:
                # shutdown is in flight: no replacement worker — fence the
                # wedged one and sweep so close()/waiters unblock instead
                # of waiting on a thread that will never return
                self._gen += 1
                stranded = self._collect_wedged_locked(include_queue=True)
                self._closed = True
                self._cond.notify_all()
                shutdown_sweep = True
            else:
                shutdown_sweep = False
                now = time.monotonic()
                if self._restarts and \
                        now - self._last_restart_mono > \
                        RESTART_BUDGET_WINDOW_S:
                    self._restarts = 0       # healthy interval: forgive
                self._last_restart_mono = now
                self._gen += 1
                new_gen = self._gen
                self._restarts += 1
            if not shutdown_sweep:
                restarts = self._restarts
                self._restarting = True
                wedged = self._collect_wedged_locked(
                    include_queue=restarts > self._max_restarts)
                hard_fail = restarts > self._max_restarts
                if hard_fail:
                    self._failed = True
                self._cond.notify_all()
        if shutdown_sweep:
            log.warning("pipeline worker wedged during shutdown (%s); "
                        "rejecting %d outstanding ticket(s)",
                        reason, len(stranded))
            self._settle([(t, None, PipelineError(
                "pipeline closed before this submission resolved "
                f"({reason})")) for t in stranded])
            return
        if hard_fail:
            exc: PipelineError = PipelineUnavailable(
                f"pipeline hard-failed after {restarts - 1} restarts "
                f"({reason}); submission rejected")
            self.metrics.inc_counter("pipeline_hard_failures_total")
        else:
            exc = PipelineError(
                f"pipeline worker restarted ({reason}); in-flight window "
                "rejected")
        self.metrics.inc_counter("pipeline_restarts_total")
        self._set_state_gauge()
        self.tracer.event("pipeline.watchdog",
                          action="hard-fail" if hard_fail else "restart",
                          reason=reason, restarts=restarts,
                          rejected=len(wedged))
        self._emit("watchdog",
                   action="hard-fail" if hard_fail else "restart",
                   reason=reason, restarts=restarts, rejected=len(wedged))
        log.warning("pipeline %s (restart %d/%d): %s; rejecting %d wedged "
                    "ticket(s)",
                    "HARD-FAILED" if hard_fail else "worker restarting",
                    restarts, self._max_restarts, reason, len(wedged))
        self._settle([(t, None, exc) for t in wedged])
        if hard_fail:
            with self._lock:
                self._restarting = False
                self._cond.notify_all()
            self._set_state_gauge()
            return
        # capped exponential backoff between restarts: a persistently
        # stalling backend gets breathing room instead of a restart storm
        time.sleep(min(self._restart_backoff_s * (1 << (restarts - 1)),
                       MAX_RESTART_BACKOFF_S))
        with self._lock:
            if self._closing or self._closed or self._gen != new_gen:
                self._restarting = False
                self._cond.notify_all()
                return
            self._worker = threading.Thread(
                target=self._run, args=(new_gen,), daemon=True,
                name=f"{self._name}-worker-g{new_gen}")
            self._worker_gen = new_gen
            self._cold_dispatch = True   # fresh gen: next dispatch is cold
            # satellite (b): recovery is DECLARED only after the new
            # worker's synthetic canary dispatch survives the real device
            # path — not merely after a thread started
            self._canary_pending = True
            self._worker.start()
            self._restarting = False
            self._cond.notify_all()
        self._set_state_gauge()

    def _on_worker_crash(self, gen: int) -> None:
        """The dying worker's own exit path (crash, not stall)."""
        with self._lock:
            if gen != self._gen:
                return               # a restart already superseded us
            shutting_down = self._closing or self._closed
        if shutting_down:
            # no restart during shutdown: sweep and mark closed so close()
            # and every waiter unblock
            stranded: List[Ticket] = []
            with self._lock:
                self._gen += 1
                stranded = self._collect_wedged_locked(include_queue=True)
                self._closed = True
                self._cond.notify_all()
            self._settle([(t, None, PipelineError(
                "pipeline worker crashed during shutdown"))
                for t in stranded])
            return
        self._restart_worker(gen, "worker crashed")

    # -- mesh self-healing (ISSUE 19) -----------------------------------------
    def _handle_device_lost(self, exc: DeviceLost,
                            slices: Sequence[_Slice],
                            buf_idx: Optional[int]) -> None:
        """A dispatch/finalize failed with a dead-accelerator signature.
        This is NOT breaker territory (retrying cannot resurrect a chip)
        and NOT watchdog territory (a restart would re-dispatch onto the
        same dead mesh): reject only the failing window's slices, PARK the
        worker — the queue and future submissions survive — and notify the
        engine, whose fenced :meth:`remesh` swaps the geometry under a
        fresh generation. Without a handler wired (bare pipelines) degrade
        to the generic dispatch-error path: breaker math still bounds the
        damage, and nothing ever parks waiting for a re-mesh that will
        never come."""
        self.dispatch_errors += 1
        self.metrics.inc_counter("pipeline_dispatch_errors_total")
        self.metrics.inc_counter(
            f'pipeline_device_lost_total{{device="{exc.device}"}}')
        cb = self._on_device_loss
        if cb is None:
            self.breaker.record_failure()
            log.warning("pipeline dispatch lost device %d with no re-mesh "
                        "handler wired; rejecting %d submission(s): %s",
                        exc.device, len(slices), exc)
            self._reject_slices(slices, exc, buf_idx)
            return
        with self._lock:
            self._device_lost = exc.device
        self._set_state_gauge()
        self.tracer.event("pipeline.device-loss", device=exc.device)
        self._emit("device-loss", device=exc.device, reason=str(exc))
        log.error("pipeline: device %d LOST (%s); worker parked pending "
                  "re-mesh, %d in-flight submission(s) rejected",
                  exc.device, exc, len(slices))
        self._reject_slices(slices, exc, buf_idx)
        try:
            cb(exc.device, str(exc))
        except Exception:   # noqa: BLE001 — a broken handler must not
            log.exception("on_device_loss handler failed")   # kill the worker

    def remesh(self, rebuild: Callable[[], Dict],
               reason: str = "device-loss") -> Dict:
        """The fenced re-mesh protocol. Fences the current generation and
        rejects ONLY the wedged in-flight window — queued submissions
        survive — then runs ``rebuild()`` (the engine's closure: re-mesh
        the datapath onto the survivor device set and re-place the active
        snapshot) and adopts the geometry it returns (``n_shards``,
        ``mesh_shards``, ``min_bucket``): seg_cap/stage_rows recomputed, a
        fresh staging ring allocated at the new shape, per-shard gauges
        swapped, and a new worker generation started with the canary
        dispatch pending.

        Unlike the watchdog protocol this NEVER spends restart budget — a
        commanded geometry change is not a crash. If ``rebuild()`` raises,
        the old geometry stands and a fresh worker restarts on it (the
        engine owns retrying); the exception propagates to the caller.
        Returns the adopted geometry dict."""
        with self._lock:
            if self._closed or self._closing:
                raise PipelineClosed("pipeline is closing; remesh refused")
            if self._failed:
                raise PipelineUnavailable(
                    "pipeline hard-failed; remesh refused")
            self._gen += 1
            new_gen = self._gen
            self._restarting = True
            self._device_lost = None
            wedged = self._collect_wedged_locked(include_queue=False)
        self.metrics.inc_counter("pipeline_remesh_total")
        self._set_state_gauge()
        self.tracer.event("pipeline.remesh", reason=reason,
                          rejected=len(wedged))
        self._settle([(t, None, PipelineError(
            f"mesh re-meshed ({reason}); in-flight window rejected"))
            for t in wedged])
        try:
            geom = rebuild() or {}
        except BaseException:
            # geometry unchanged: restart a worker on the OLD shape so
            # queued submissions are served (or fail back into the park
            # path if the mesh really is dead — the engine retries)
            self._start_generation(new_gen)
            self._emit("remesh", reason=reason, ok=False,
                       rejected=len(wedged))
            raise
        with self._lock:
            n_shards = int(geom.get("n_shards", self._n_shards))
            mesh_shards = int(geom.get("mesh_shards", n_shards))
            min_bucket = _next_pow2(
                int(geom.get("min_bucket", self._min_bucket)))
            self._n_shards = n_shards
            self._mesh_shards = mesh_shards if mesh_shards > 0 else n_shards
            self._min_bucket = min(min_bucket, self._max_bucket)
            if n_shards > 1:
                self._seg_cap = min(self._max_bucket, _next_pow2(
                    max(1, self._max_bucket // n_shards)
                    * self._shard_headroom))
                self._stage_rows = n_shards * self._seg_cap
            else:
                self._seg_cap = 0
                self._stage_rows = self._max_bucket
            old_gauges = self._shard_gauge_names
            self._shard_gauge_names = [
                f'pipeline_staged_rows{{shard="{s}"}}'
                for s in range(n_shards)] if n_shards > 1 else []
            self._shard_fill = [0] * n_shards
            self._shard_rows_total = [0] * n_shards
            self._stage_steer_rev = None
            # fresh ring at the NEW geometry (the wedged-collect above
            # already re-allocated one, but at the old shape)
            self._buffers = [_StageBuf(self._stage_rows, n_shards)
                             for _ in range(self._inflight_max + 1)]
            self._free_bufs = list(range(len(self._buffers)))
            self.metrics.set_gauge("pipeline_staging_free",
                                   len(self._free_bufs))
            if self._mesh_shards > 1:
                self.metrics.set_gauge("pipeline_mesh_shards",
                                       self._mesh_shards)
        # departed-shard gauge sweep: a 4→3 remesh must not leave
        # shard="3" pinned at its last fill forever
        for name in old_gauges:
            if name not in self._shard_gauge_names:
                self.metrics.drop_gauge(name)
        self._start_generation(new_gen)
        self._emit("remesh", reason=reason, ok=True, n_shards=n_shards,
                   mesh_shards=self._mesh_shards, rejected=len(wedged))
        log.warning("pipeline re-meshed (%s): n_shards=%d mesh_shards=%d "
                    "min_bucket=%d; %d wedged ticket(s) rejected",
                    reason, n_shards, self._mesh_shards, self._min_bucket,
                    len(wedged))
        return {"n_shards": self._n_shards,
                "mesh_shards": self._mesh_shards,
                "min_bucket": self._min_bucket,
                "rejected": len(wedged)}

    def _start_generation(self, new_gen: int) -> None:
        """Start a fresh worker for ``new_gen`` (remesh path — no restart
        budget, no backoff) with the canary dispatch pending; clears
        ``_restarting`` either way."""
        with self._lock:
            if not (self._closing or self._closed or self._gen != new_gen):
                self._worker = threading.Thread(
                    target=self._run, args=(new_gen,), daemon=True,
                    name=f"{self._name}-worker-g{new_gen}")
                self._worker_gen = new_gen
                self._cold_dispatch = True
                self._canary_pending = True
                self._worker.start()
            self._restarting = False
            self._cond.notify_all()
        self._set_state_gauge()

    def _maybe_canary(self, gen: int) -> None:
        """A restarted/re-meshed worker's first act: prove the device path
        with a synthetic all-invalid dispatch BEFORE serving traffic — a
        recovery that immediately wedges again must never eat a real
        submission to find out. The batch carries a ``_canary`` marker
        column so the engine's dispatch closure skips its observers (flow
        log, parity auditor, CT fingerprints). Success closes the half-open
        breaker the same way a real dispatch would; failure feeds the
        breaker — or the device-loss park path — with zero tickets harmed.
        The canary does not count as a dispatched/completed batch."""
        with self._lock:
            if not self._canary_pending or gen != self._gen:
                return
            self._canary_pending = False
        rows = self._n_shards if self._n_shards > 1 else 1
        batch = empty_batch(rows)
        batch["_canary"] = np.ones(rows, dtype=np.uint8)
        now = int(time.time())
        try:
            self._hb_arm("canary", gen, grace=COLD_DISPATCH_GRACE)
            self._check_gen(gen)
            if self._n_shards > 1:
                finalize = self._dispatch_fn(batch, now, None)
            else:
                finalize = self._dispatch_fn(batch, now)
            finalize()
            self._hb_clear(gen)
            self._check_gen(gen)
        except _Superseded:
            raise
        except DeviceLost as e:
            self._hb_clear(gen)
            self._check_gen(gen)
            self.metrics.inc_counter("pipeline_canary_failed_total")
            log.warning("pipeline canary (gen %d) lost device %d: %s",
                        gen, e.device, e)
            self._handle_device_lost(e, (), None)
            return
        except Exception as e:   # noqa: BLE001 — counted; breaker owns it
            self._hb_clear(gen)
            self._check_gen(gen)
            self.metrics.inc_counter("pipeline_canary_failed_total")
            self.breaker.record_failure()
            log.warning("pipeline canary (gen %d) failed: %s", gen, e)
            return
        self.metrics.inc_counter("pipeline_canary_ok_total")
        if self.breaker.state != "closed":
            self.breaker.record_success()
        self._cold_dispatch = False

    def _shed(self, ticket: Ticket, reason: str,
              exc: Optional[BaseException] = None) -> None:
        """Shed one submission without computing it (deadline passed, or a
        steer-overflow batch no shard segment can hold). Counted per shed
        point in ``pipeline_shed_total``; default rejection is the deadline
        error, ``exc`` overrides (steer overflow rejects with
        :class:`PipelineDrop` — overload shed, retryable)."""
        with self._lock:
            self.shed_total += 1
            self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        # the reason-only family counts every shed, QoS on or off, so
        # pre-QoS dashboards/alerts keep working when QoS is armed; with
        # QoS the shed is ALSO attributed to the ticket's tenant (the
        # name rode the ticket from admission, no table lookup here) in a
        # labeled family alongside it, never instead of it
        self.metrics.inc_counter(
            f'pipeline_shed_total{{reason="{reason}"}}')
        if ticket.tenant is not None:
            self.metrics.inc_counter(
                f'pipeline_shed_total{{reason="{reason}",'
                f'tenant="{ticket.tenant}"}}')
        self.tracer.record(ticket.trace_id, "pipeline.shed",
                           ticket.submitted_mono,
                           time.monotonic() - ticket.submitted_mono,
                           {"reason": reason})
        self._emit("shed", reason=reason, seq=ticket.seq)
        if exc is None:
            exc = PipelineDeadlineExceeded(
                f"deadline exceeded before {reason} (seq={ticket.seq}, "
                f"waited "
                f"{(time.monotonic() - ticket.submitted_mono) * 1e3:.1f}ms)")
        self._settle([(ticket, None, exc)])

    # -- worker side ----------------------------------------------------------
    def _run(self, gen: int) -> None:
        try:
            self._run_inner(gen)
        except _Superseded:
            return                       # fenced off; replacement owns state
        except BaseException:            # noqa: BLE001 — never strand tickets
            log.exception("pipeline worker (gen %d) died", gen)
            self._on_worker_crash(gen)

    def _run_inner(self, gen: int) -> None:
        self._maybe_canary(gen)
        while True:
            sub = None
            action = None
            with self._lock:
                while True:
                    if gen != self._gen or self._closed:
                        return
                    if self._device_lost is not None and not self._closing:
                        # device-lost park: do NOT pop the queue — queued
                        # submissions must survive until Pipeline.remesh()
                        # supersedes this generation and a fresh worker
                        # serves them on the survivor mesh. (During close
                        # we fall through so shutdown can still sweep.)
                        self._cond.wait(0.25)
                        continue
                    if self._queue:
                        sub = self._queue.popleft()
                        # hand-off under the lock: the sub must never be
                        # in neither the queue nor _current when a
                        # close/watchdog sweep runs
                        self._current = sub
                        depth = len(self._queue)
                        self.metrics.set_gauge("pipeline_queue_depth", depth)
                        if depth >= self._queue_max - 1:
                            self._cond.notify_all()   # wake blocked producers
                        action = "ingest"
                        break
                    if self._staged_slices and (
                            self._drain_req or self._closing
                            or time.monotonic() >= self._stage_deadline):
                        action = ("drain" if (self._drain_req
                                              or self._closing)
                                  else "deadline")
                        break
                    if self._inflight:
                        # idle with work in flight: finalize eagerly so a
                        # lone submission never waits for a successor
                        action = "finalize"
                        break
                    if self._closing:
                        return
                    wait = None
                    if self._staged_slices:
                        wait = max(0.0, self._stage_deadline
                                   - time.monotonic())
                    self._cond.wait(wait)
            if action == "ingest":
                self._ingest(sub, gen)     # _current was set at the pop
                self._current = None
            elif action == "finalize":
                self._finalize_oldest(gen)
            else:
                self._flush(action, gen)

    def _ingest(self, sub: _Sub, gen: int) -> None:
        t = sub.ticket
        if t.deadline_mono is not None \
                and time.monotonic() > t.deadline_mono:
            self._shed(t, "ingest")
            return
        m = t.n_valid
        if m == 0:
            # nothing to classify: resolve without a device round trip
            wait = time.monotonic() - t.submitted_mono
            self.metrics.histogram("pipeline_queue_wait_seconds").observe(
                wait)
            self.tracer.record(t.trace_id, "pipeline.admission",
                               t.submitted_mono, wait)
            self._settle([(t, _zero_out(t.n_rows), None)])
            return
        # latency lane: a lane-tagged tenant's submission never waits out
        # the coalesce deadline — it dispatches the moment it stages (at
        # the small always-armed lane bucket), taking any staged bulk
        # riders along. Bulk tenants keep the deadline microbatching.
        lane = bool(self._lane_bucket) and self._qos is not None \
            and self._qos.is_lane(sub.tenant)
        if self._n_shards > 1:
            # sharded staging: every row must land in its flow shard's
            # segment, so even bucket-shaped submissions stage (no direct
            # bypass — an arbitrary row order carries no shard placement)
            self._ingest_sharded(sub, gen, lane=lane)
            return
        rows = t.n_rows
        if (self._staged_rows == 0
                and (self._lane_bucket if lane
                     else self._min_bucket) <= rows <= self._max_bucket
                and rows & (rows - 1) == 0):
            # already bucket-shaped: zero-copy direct dispatch (_current
            # stays set across the hand-off into _dispatching — a ticket
            # is always visible in at least one sweep registry)
            self._dispatch(sub.batch, sub.now,
                           [_Slice(t, None, 0)], rows, m, "direct", None,
                           gen)
            return
        if self._staged_rows + m > self._max_bucket:
            self._flush("full", gen)
        if self._stage_buf is None:
            self._stage_buf = self._acquire_buffer(gen)
            # the deadline is anchored to the oldest rider's SUBMIT time so
            # backlogged submissions flush immediately instead of waiting
            # another full window
            self._stage_deadline = t.submitted_mono + self._flush_s
            self._stage_now = None
        valid_idx = np.nonzero(np.asarray(sub.batch["valid"]))[0]
        buf = self._buffers[self._stage_buf].cols
        pos = self._staged_rows
        with self.tracer.span(t.trace_id, "pipeline.microbatch", rows=m):
            # pipeline.stage_write: just the column writes into the pinned
            # staging slot — the per-stage attribution point the ingest
            # bench reads (microbatch additionally covers valid_idx/admin)
            with self.tracer.span(t.trace_id, "pipeline.stage_write",
                                  rows=m, slot=self._stage_buf):
                for k, col in buf.items():
                    if k.startswith("_"):
                        # optional shim-side column: absent in non-shim
                        # submissions → 0 ("no raw id")
                        src = sub.batch.get(k)
                        if src is None:
                            col[pos:pos + m] = 0
                            continue
                    else:
                        src = sub.batch[k]   # required: missing → crash →
                        #                      supervised reject (pinned)
                    col[pos:pos + m] = np.asarray(src)[valid_idx]
        if self._stage_now is None:
            self._stage_now = sub.now
        self._staged_slices.append(_Slice(t, valid_idx, pos))
        self._staged_rows += m
        self._publish(gen)
        if lane:
            self._flush("lane", gen)
        elif self._staged_rows >= self._max_bucket:
            self._flush("full", gen)

    def _shards_for(self, batch: Dict[str, np.ndarray],
                    valid_idx: np.ndarray, rev: int) -> np.ndarray:
        """Flow-shard id per valid row. A producer that already hashed
        (the shim feeder's harvest pre-binning — the SHARD_BIN encoding:
        low bits shard+1, 0 = not binned; high bits the policy revision
        the bin was hashed under) skips the hash entirely — but ONLY when
        the bin's revision matches ``rev``, the revision the caller read
        BEFORE steering and will stamp the bucket with: a regen between
        harvest and stage-write can change the LB tables and with them a
        service flow's post-DNAT steer hash, and a stale bin would strand
        its CT entry on the wrong shard. (Reading the revision once,
        up-front, also means a regen landing DURING this call can at worst
        stamp the bucket with the older revision — forcing a dispatch-time
        re-steer — never accept stale rows under a fresh stamp.) Anything
        else goes through ``shard_fn`` (the engine's direction-normalized
        flow hash over the active snapshot's LB tables)."""
        col = batch.get("_shard")
        if col is not None:
            raw = np.asarray(col)[valid_idx].astype(np.int64)
            pre = (raw & SHARD_BIN_MASK) - 1
            if pre.size and pre.min() >= 0 \
                    and pre.max() < self._n_shards \
                    and (self._shard_rev_fn is None
                         or bool((raw >> SHARD_BIN_SHIFT
                                  == (rev & SHARD_BIN_REV_MASK)).all())):
                return pre
        shard = np.asarray(self._shard_fn(batch), dtype=np.int64)
        return shard[valid_idx]

    def _ingest_sharded(self, sub: _Sub, gen: int,
                        lane: bool = False) -> None:
        """Steered staging (the software-RSS half of the multi-chip path):
        each valid row is scattered directly into its flow shard's column
        segment, so flush hands the datapath an already-steered batch and
        the per-batch steer→allocate→pack chain never runs. Placement is
        ``steer_rows`` — byte-identical to what ``steer_batch`` would
        produce for the same arrival order, which is what makes 8-shard
        pipeline verdicts bit-identical to the single-chip path."""
        t = sub.ticket
        m = t.n_valid
        valid_idx = np.nonzero(np.asarray(sub.batch["valid"]))[0]
        # the bucket's steer-revision stamp is read BEFORE hashing: a
        # regen landing mid-steer then stamps the bucket with the OLDER
        # revision (dispatch re-steers), never blesses stale rows
        rev = self._shard_rev_fn() if self._shard_rev_fn is not None else 0
        with self.tracer.span(t.trace_id, "pipeline.steer", rows=m):
            shard = self._shards_for(sub.batch, valid_idx, rev)
            counts = np.bincount(shard, minlength=self._n_shards)
        if int(counts.max()) > self._seg_cap:
            # one pathologically skewed submission can never fit a shard
            # segment: shed with an attributable reason instead of letting
            # the old per_shard ValueError crash the worker into a
            # watchdog restart
            self._shed(t, "steer_overflow", PipelineDrop(
                f"steer overflow: {int(counts.max())} rows for one flow "
                f"shard exceed the per-shard segment capacity "
                f"{self._seg_cap} (seq={t.seq})"))
            return
        if self._staged_slices and bool(
                (np.asarray(self._shard_fill) + counts
                 > self._seg_cap).any()):
            self._flush("full", gen)
        if self._stage_buf is None:
            self._stage_buf = self._acquire_buffer(gen)
            self._stage_deadline = t.submitted_mono + self._flush_s
            self._stage_now = None
            self._stage_steer_rev = rev
        elif self._stage_steer_rev != rev:
            self._stage_steer_rev = -2       # mixed: dispatch must re-steer
        stage = self._buffers[self._stage_buf]
        buf = stage.cols
        fills = self._shard_fill
        with self.tracer.span(t.trace_id, "pipeline.microbatch", rows=m):
            with self.tracer.span(t.trace_id, "pipeline.stage_write",
                                  rows=m, slot=self._stage_buf):
                dst_rows = steer_rows(shard, self._n_shards, self._seg_cap,
                                      fills, counts=counts)
                for k, col in buf.items():
                    if k.startswith("_"):
                        src = sub.batch.get(k)
                        if src is None:
                            col[dst_rows] = 0
                            continue
                    else:
                        src = sub.batch[k]
                    col[dst_rows] = np.asarray(src)[valid_idx]
        for s in range(self._n_shards):
            c = int(counts[s])
            if c:
                fills[s] += c
                self._shard_rows_total[s] += c
                stage.dirty[s] = max(stage.dirty[s], fills[s])
        if self._stage_now is None:
            self._stage_now = sub.now
        self._staged_slices.append(_Slice(t, valid_idx, 0,
                                          dst_rows=dst_rows))
        self._staged_rows += m
        self._publish(gen)
        if lane:
            # the sharded dispatch shape is the fixed steered layout, so
            # the lane here only skips the coalesce deadline — no shape
            # change, no extra XLA traces
            self._flush("lane", gen)
        elif max(fills) >= self._seg_cap:
            self._flush("full", gen)

    def _flush(self, reason: str, gen: int) -> None:
        if not self._staged_slices:
            return
        buf_idx = self._stage_buf
        stage = self._buffers[buf_idx]
        buf = stage.cols
        rows = self._staged_rows
        slices = self._staged_slices
        now = self._stage_now
        sharded = self._n_shards > 1
        steer_rev = self._stage_steer_rev
        self._stage_steer_rev = None
        if sharded:
            fills = self._shard_fill
            self._shard_fill = [0] * self._n_shards
        # hand-off ordering: into _dispatching BEFORE leaving the staged
        # registry, so a concurrent sweep always sees every ticket
        self._dispatching = slices
        self._stage_buf = None
        self._staged_rows = 0
        self._staged_slices = []
        self._stage_now = None
        # deadline shed at flush time: riders whose deadline passed while
        # coalescing are masked out of the bucket and rejected — the
        # device never spends a cycle on them
        now_mono = time.monotonic()
        live: List[_Slice] = []
        for sl in slices:
            dl = sl.ticket.deadline_mono
            if dl is not None and now_mono > dl:
                if sl.dst_rows is not None:
                    buf["valid"][sl.dst_rows] = False
                else:
                    n = len(sl.valid_idx)
                    buf["valid"][sl.dst_start:sl.dst_start + n] = False
                self._shed(sl.ticket, "flush")
            else:
                live.append(sl)
        if not live:
            self._dispatching = []       # every slice settled by _shed
            self._recycle(buf_idx)
            self._publish(gen)
            return
        n_valid = sum(len(sl.valid_idx) for sl in live)
        if sharded:
            # restore empty-batch defaults on each segment's stale tail
            # (rows a previous, fuller use of this buffer wrote past the
            # current fill) — same wire-format-probe poisoning guard as
            # the unsharded tail reset, segment by segment. The dispatch
            # shape is always the full steered layout: one trace per wire
            # format, padded tails are valid-masked.
            for s in range(self._n_shards):
                base = s * self._seg_cap
                if fills[s] < stage.dirty[s]:
                    reset_batch_rows(buf, base + fills[s],
                                     base + stage.dirty[s])
                    stage.dirty[s] = fills[s]
            bucket = self._stage_rows
        else:
            # lane flushes dispatch at the (smaller) lane floor — padding
            # a 4-row lane batch to min_bucket would spend the latency
            # budget the lane exists to protect
            floor = (self._lane_bucket if reason == "lane"
                     and self._lane_bucket else self._min_bucket)
            bucket = max(floor, _next_pow2(rows))
            if rows < bucket:
                # reused buffer: restore the empty-batch defaults on the
                # tail, not just the valid mask — stale v6/L7/_ep_raw
                # content from an earlier, larger flush would otherwise
                # poison the datapath's wire-format probes (sticking the
                # wide wire forever) and trip the strict v6 check in the
                # compact pack kernel
                reset_batch_rows(buf, rows, bucket)
        self._dispatch(stage.view(bucket), now, live, bucket, n_valid,
                       reason, buf_idx, gen, steer_rev=steer_rev)

    def _dispatch(self, batch: Dict[str, np.ndarray], now: Optional[int],
                  slices: List[_Slice], bucket_rows: int, n_valid: int,
                  reason: str, buf_idx: Optional[int], gen: int,
                  steer_rev: Optional[int] = None) -> None:
        # hand-off ordering invariant: these slices are in _dispatching
        # from before they leave any upstream registry until after they
        # are settled or appended to _inflight — a concurrent sweep can
        # never catch a ticket in no registry at all
        self._dispatching = slices
        if now is None:
            now = int(time.time())
        if self.breaker.state == "open":
            # opened while this batch staged/queued: reject fast rather
            # than hammering the sick backend with its rows
            self._count_unavailable()
            self._reject_slices(slices, PipelineUnavailable(
                "circuit breaker open; dispatch suppressed"), buf_idx)
            self._dispatching = []
            return
        self.flush_reasons[reason] = self.flush_reasons.get(reason, 0) + 1
        self.metrics.inc_counter(f"pipeline_flush_{reason}_total")
        self._fill_rows += n_valid
        self._bucket_rows += bucket_rows
        if reason == "lane":
            # lane-only fill accounting: the autotuner's lane/bulk
            # arbitration reads padding waste from these, separately from
            # the aggregate fill ratio the bulk knobs are tuned by
            self._lane_fill_rows += n_valid
            self._lane_bucket_rows += bucket_rows
        self.metrics.set_gauge("pipeline_fill_ratio",
                               round(n_valid / bucket_rows, 4))
        t0 = time.monotonic()
        qw = self.metrics.histogram("pipeline_queue_wait_seconds")
        lw = (self.metrics.histogram("pipeline_lane_wait_seconds")
              if reason == "lane" else None)
        for sl in slices:
            qw.observe(t0 - sl.ticket.submitted_mono)
            if lw is not None:
                lw.observe(t0 - sl.ticket.submitted_mono)
            self.tracer.record(sl.ticket.trace_id, "pipeline.admission",
                               sl.ticket.submitted_mono,
                               t0 - sl.ticket.submitted_mono)
        # the batch-level spans ride the first sampled rider's trace; the
        # trace context makes the datapath's pack/transfer/compute split
        # attach to the same trace id across the backend boundary
        tid = next((sl.ticket.trace_id for sl in slices
                    if sl.ticket.trace_id is not None), None)

        attempts = 0
        while True:
            try:
                self._hb_arm(self._hb_dispatch_label, gen,
                             grace=COLD_DISPATCH_GRACE
                             if self._cold_dispatch else 1)
                FAULTS.fire("pipeline.dispatch")
                # a fenced-off worker released from a hang-mode stall must
                # not dispatch: its window was already rejected — reaching
                # the datapath now would mutate CT for nobody
                self._check_gen(gen)
                with self.tracer.context(tid), \
                        self.tracer.span(tid, "pipeline.dispatch",
                                         bucket=bucket_rows,
                                         n_valid=n_valid, reason=reason):
                    if self._n_shards > 1:
                        # sharded dispatch_fns take the steer revision so
                        # the backend can detect a regen landing between
                        # stage-write and here and re-steer under the
                        # snapshot it classifies with
                        finalize = self._dispatch_fn(batch, now, steer_rev)
                    else:
                        finalize = self._dispatch_fn(batch, now)
                self._hb_clear(gen)
                self._check_gen(gen)
                break
            except FaultInjected as e:
                self._hb_clear(gen)
                self._check_gen(gen)
                self.dispatch_faults += 1
                self.metrics.inc_counter("pipeline_dispatch_faults_total")
                attempts += 1
                if self.breaker.record_failure():
                    # the breaker opened: stop burning the retry budget
                    # against a backend that is failing every attempt
                    self._count_unavailable()
                    self._reject_slices(slices, PipelineUnavailable(
                        f"circuit breaker opened after {attempts} dispatch "
                        f"attempts: {e}"), buf_idx)
                    self._dispatching = []
                    return
                cap = (MAX_DISPATCH_RETRIES_CLOSING if self._closing
                       else MAX_DISPATCH_RETRIES)
                if attempts >= cap:
                    self._reject_slices(slices, e, buf_idx)
                    self._dispatching = []
                    return
                time.sleep(min(0.05, 0.0005 * (1 << min(attempts, 7))))
            except DeviceLost as e:
                self._hb_clear(gen)
                self._check_gen(gen)
                self._handle_device_lost(e, slices, buf_idx)
                self._dispatching = []
                return
            except Exception as e:   # noqa: BLE001 — supervised degradation
                self._hb_clear(gen)
                self._check_gen(gen)
                self.dispatch_errors += 1
                self.metrics.inc_counter("pipeline_dispatch_errors_total")
                self.breaker.record_failure()
                log.warning("pipeline dispatch failed, rejecting %d "
                            "submission(s): %s", len(slices), e)
                self._reject_slices(slices, e, buf_idx)
                self._dispatching = []
                return
        # a successful dispatch is only an *enqueue* — the failure streak
        # resets on finalize (the device actually answering). The
        # exception is the half-open probe: its dispatch succeeding is the
        # close signal (the issue's "half-open probe dispatches close it")
        if self.breaker.state != "closed":
            self.breaker.record_success()
        self._cold_dispatch = False      # this generation is warm now
        self.dispatched_batches += 1
        self._inflight.append(_Inflight(finalize, slices, t0, buf_idx))
        self._dispatching = []           # now visible in _inflight
        self.metrics.set_gauge("pipeline_inflight", len(self._inflight))
        self._publish(gen)
        # keep at most ``inflight`` batches genuinely in flight; the ring
        # has inflight+1 staging buffers so the next microbatch can stage
        # while the window is full
        while len(self._inflight) > self._inflight_max:
            self._finalize_oldest(gen)

    def _finalize_oldest(self, gen: int) -> None:
        if not self._inflight:
            return
        # hand-off ordering: into _finalizing BEFORE leaving _inflight
        inf: _Inflight = self._inflight[0]
        self._finalizing = inf
        self._inflight.popleft()
        tid = next((sl.ticket.trace_id for sl in inf.slices
                    if sl.ticket.trace_id is not None), None)
        try:
            self._hb_arm(self._hb_finalize_label, gen)
            FAULTS.fire("pipeline.finalize")
            self._check_gen(gen)     # hang-released fence: do not finalize
            with self.tracer.context(tid), \
                    self.tracer.span(tid, "pipeline.finalize"):
                out = inf.finalize()
            self._hb_clear(gen)
        except DeviceLost as e:
            self._hb_clear(gen)
            self._check_gen(gen)
            self._handle_device_lost(e, inf.slices, inf.buf_idx)
            self._finalizing = None      # settled above
            return
        except Exception as e:   # noqa: BLE001 — incl. injected trips
            self._hb_clear(gen)
            self._check_gen(gen)
            self.dispatch_errors += 1
            self.metrics.inc_counter("pipeline_dispatch_errors_total")
            self.breaker.record_failure()
            log.warning("pipeline finalize failed, rejecting %d "
                        "submission(s): %s", len(inf.slices), e)
            self._reject_slices(inf.slices, e, inf.buf_idx)
            self._finalizing = None      # settled above
            return
        self._check_gen(gen)
        self.breaker.record_success()
        self.metrics.histogram("pipeline_batch_latency_seconds").observe(
            time.monotonic() - inf.t_dispatch)
        outcomes = []
        for sl in inf.slices:
            if sl.valid_idx is None:        # direct: geometry already matches
                outcomes.append((sl.ticket, out, None))
                continue
            n = len(sl.valid_idx)
            tout = _zero_out(sl.ticket.n_rows)
            for k, arr in out.items():
                if k not in tout:
                    tout[k] = np.zeros((sl.ticket.n_rows,) + arr.shape[1:],
                                       dtype=arr.dtype)
                # steered buckets: gathering through dst_rows un-steers
                # this ticket's verdicts back into submission row order
                if sl.dst_rows is not None:
                    tout[k][sl.valid_idx] = arr[sl.dst_rows]
                else:
                    tout[k][sl.valid_idx] = arr[sl.dst_start:
                                                sl.dst_start + n]
            outcomes.append((sl.ticket, tout, None))
        self.completed_batches += 1
        self._recycle(inf.buf_idx)
        self.metrics.set_gauge("pipeline_inflight", len(self._inflight))
        self._publish(gen)
        self._settle(outcomes)
        self._finalizing = None          # settled above

    # -- small helpers ---------------------------------------------------------
    def _publish(self, gen: int) -> None:
        """Worker-side: publish a consistent snapshot of the worker-owned
        stats under the lock (what ``stats()`` reads instead of racing the
        worker's in-flux fields)."""
        snapshot = {
            "staged_rows": self._staged_rows,
            "flush_reasons": dict(self.flush_reasons),
            "fill_rows": self._fill_rows,
            "bucket_rows": self._bucket_rows,
            "inflight": len(self._inflight),
            "staging_free": len(self._free_bufs),
            "staging_slots": len(self._buffers),
            "dispatched_batches": self.dispatched_batches,
            "completed_batches": self.completed_batches,
        }
        if self._qos is not None:
            snapshot["lane_fill_rows"] = self._lane_fill_rows
            snapshot["lane_bucket_rows"] = self._lane_bucket_rows
        if self._n_shards > 1:
            snapshot["shard_fill"] = list(self._shard_fill)
            snapshot["shard_rows_total"] = list(self._shard_rows_total)
        with self._lock:
            if gen != self._gen:         # a fenced worker must not publish
                return
            self._pub = snapshot
            # shard-labeled staging occupancy: which segment is the
            # skew/backpressure hotspot (the per-mesh guard surface).
            # Inside the gen-checked lock so a fenced worker can never
            # overwrite the restart sweep's gauge reset with stale fills
            # (metrics locks are leaves — same nesting as the sweep's own
            # gauge writes); names precomputed, once per ingest.
            for name, f in zip(self._shard_gauge_names,
                               snapshot.get("shard_fill", ())):
                self.metrics.set_gauge(name, f)

    def _acquire_buffer(self, gen: int) -> int:
        while not self._free_bufs:
            self._check_gen(gen)
            self._finalize_oldest(gen)
        idx = self._free_bufs.pop()
        # staging-ring occupancy: free slots left after this acquire (0 =
        # every slot staged or in flight — the host is the bottleneck)
        self.metrics.set_gauge("pipeline_staging_free", len(self._free_bufs))
        return idx

    def _recycle(self, buf_idx: Optional[int]) -> None:
        if buf_idx is not None:
            self._free_bufs.append(buf_idx)
            self.metrics.set_gauge("pipeline_staging_free",
                                   len(self._free_bufs))

    def _reject_slices(self, slices: Sequence[_Slice], exc: BaseException,
                       buf_idx: Optional[int]) -> None:
        wrapped = exc if isinstance(exc, PipelineError) else \
            PipelineError(f"dispatch failed: {type(exc).__name__}: {exc}")
        wrapped.__cause__ = exc
        self._recycle(buf_idx)
        self._settle([(sl.ticket, None, wrapped) for sl in slices])
