"""Pipelined ingestion scheduler: overlapped host→device batching runtime.

BENCH_r05 showed the serving path ~30-60x off its own compute ceiling:
``compute_only`` runs ~300M flows/sec/chip while the end-to-end path sits at
6-9M, because a batch is built, transferred, and classified strictly
serially. This subsystem is the continuous-batching layer between the shim
and the datapath that closes that shape problem:

- **Admission with backpressure** (``submit``): a bounded multi-producer
  queue. When full, producers either block up to a timeout or shed
  immediately (``admission="drop"``) — never unbounded blocking, and every
  shed submission is accounted (``pipeline_admission_drops_total``).
- **Deadline-based microbatching**: sub-full submissions coalesce in a host
  staging buffer until either the buffer fills or the oldest submission's
  deadline (``flush_ms``) expires. Dispatch shapes are drawn from a small
  set of power-of-two buckets in ``[min_bucket, max_bucket]`` so the device
  sees a handful of stable shapes (no recompile storms). A submission that
  already *is* a bucket shape bypasses staging entirely (zero-copy
  ``direct`` dispatch).
- **Overlap** (double/ring-buffered staging): dispatch goes through
  ``DatapathBackend.classify_async`` — the JIT backend enqueues pack +
  transfer + XLA dispatch and returns a finalize callable, so the worker
  stages and transfers batch *i+1* while the device still computes batch
  *i* (up to ``inflight`` batches in flight; CT buffer donation sequences
  the steps on-device). On FakeDatapath classify_async is synchronous — a
  plain queue, same semantics, no overlap.
- **Ordering**: one worker drains the queue FIFO and finalizes in-flight
  batches FIFO, so CT mutation order == submission order and every ticket
  resolves in order. This is what makes pipeline verdicts bit-identical to
  the serial ``classify`` path on the same submissions.
- **Telemetry**: queue depth / inflight gauges, admission drops, flush
  reasons, fill ratio, and ``pipeline_queue_wait_seconds`` /
  ``pipeline_batch_latency_seconds`` histograms through ``Metrics``.

Fault injection: every dispatch fires the ``pipeline.dispatch`` point.
``FaultInjected`` trips are retried with a capped backoff (counted in
``pipeline_dispatch_faults_total``) — an armed chaos scenario delays
batches but never loses or reorders them. Non-fault dispatch errors reject
only the affected tickets; the pipeline keeps serving (supervised
degradation, same philosophy as the engine's regen path).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.kernels.records import empty_batch
from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.runtime.metrics import Metrics

log = logging.getLogger("cilium_tpu.pipeline")

#: retry caps for FaultInjected dispatch trips (the closing cap bounds
#: shutdown time when a fail-always fault is armed)
MAX_DISPATCH_RETRIES = 1000
MAX_DISPATCH_RETRIES_CLOSING = 25

# canonical out columns (the DatapathBackend.classify contract) — used to
# resolve all-invalid submissions without a device round trip
_OUT_SPEC: Tuple[Tuple[str, type, Tuple[int, ...]], ...] = (
    ("allow", bool, ()), ("reason", np.int32, ()), ("status", np.int32, ()),
    ("remote_identity", np.int32, ()), ("redirect", bool, ()),
    ("svc", bool, ()), ("nat_dst", np.uint32, (4,)),
    ("nat_dport", np.int32, ()), ("rnat", bool, ()),
    ("rnat_src", np.uint32, (4,)), ("rnat_sport", np.int32, ()),
)


def _zero_out(n: int) -> Dict[str, np.ndarray]:
    return {k: np.zeros((n,) + shape, dtype=dt) for k, dt, shape in _OUT_SPEC}


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class PipelineError(RuntimeError):
    """Base error for pipeline submissions."""


class PipelineDrop(PipelineError):
    """Submission shed at admission (queue full, drop mode or block
    timeout exhausted)."""


class PipelineClosed(PipelineError):
    """submit() after close()."""


class Ticket:
    """Handle for one submission. ``result()`` blocks until the pipeline
    resolved this submission's rows and returns the out dict (same row
    geometry as the submitted batch; invalid rows zero-filled, exactly like
    the serial classify path)."""

    __slots__ = ("seq", "n_rows", "n_valid", "submitted_mono", "trace_id",
                 "_event", "_out", "_exc")

    def __init__(self, n_rows: int, n_valid: int):
        self.seq = -1                      # assigned at admission
        self.n_rows = n_rows
        self.n_valid = n_valid
        self.trace_id = None               # observe/trace sampling decision
        self.submitted_mono = time.monotonic()
        self._event = threading.Event()
        self._out: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def dropped(self) -> bool:
        return isinstance(self._exc, PipelineDrop)

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"pipeline ticket seq={self.seq} not resolved "
                               f"within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._out

    # -- pipeline-internal ---------------------------------------------------
    def _resolve(self, out: Dict[str, np.ndarray]) -> None:
        self._out = out
        self._event.set()

    def _reject(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()


class _Sub:
    """One admitted submission riding the queue. ``valid_idx`` is computed
    lazily on the worker — the direct-dispatch fast path never needs it."""

    __slots__ = ("ticket", "batch", "now")

    def __init__(self, ticket: Ticket, batch: Dict[str, np.ndarray],
                 now: Optional[int]):
        self.ticket = ticket
        self.batch = batch
        self.now = now


class _Slice:
    """A submission's rows inside one dispatched bucket. ``valid_idx`` is
    None for a direct (zero-copy) dispatch: the out arrays already have the
    submission's row geometry."""

    __slots__ = ("ticket", "valid_idx", "dst_start")

    def __init__(self, ticket: Ticket, valid_idx: Optional[np.ndarray],
                 dst_start: int):
        self.ticket = ticket
        self.valid_idx = valid_idx
        self.dst_start = dst_start


class _Inflight:
    __slots__ = ("finalize", "slices", "t_dispatch", "buf_idx")

    def __init__(self, finalize, slices, t_dispatch, buf_idx):
        self.finalize = finalize
        self.slices = slices
        self.t_dispatch = t_dispatch
        self.buf_idx = buf_idx


class Pipeline:
    """The scheduler. ``dispatch_fn(batch, now)`` must enqueue one batch and
    return a zero-arg finalize callable yielding the out dict — the Engine
    provides a closure over ``DatapathBackend.classify_async`` that also
    feeds metrics and the flow log.

    Producers call :meth:`submit` from any thread; one worker thread owns
    staging, dispatch, and finalization, which is what guarantees CT-order
    == submission-order."""

    def __init__(self, dispatch_fn: Callable, *,
                 metrics: Optional[Metrics] = None,
                 max_bucket: int = 8192, min_bucket: int = 256,
                 queue_batches: int = 64, admission: str = "block",
                 block_timeout_s: float = 1.0, flush_ms: float = 2.0,
                 inflight: int = 2, name: str = "pipeline",
                 tracer: Optional[Tracer] = None):
        if max_bucket & (max_bucket - 1) or max_bucket <= 0:
            raise ValueError("max_bucket must be a power of two")
        if min_bucket & (min_bucket - 1) or not 0 < min_bucket <= max_bucket:
            raise ValueError("min_bucket must be a power of two "
                             "<= max_bucket")
        if admission not in ("block", "drop"):
            raise ValueError(f"bad admission mode {admission!r}")
        if inflight < 1 or queue_batches < 1:
            raise ValueError("inflight and queue_batches must be >= 1")
        self._dispatch_fn = dispatch_fn
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else TRACER
        self._max_bucket = max_bucket
        self._min_bucket = min_bucket
        self._queue_max = queue_batches
        self._admission = admission
        self._block_timeout_s = block_timeout_s
        self._flush_s = flush_ms / 1e3
        self._inflight_max = inflight

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._outstanding = 0            # accepted tickets not yet resolved
        self._drain_req = 0
        self._closing = False
        self._closed = False
        self._next_seq = 0

        # worker-owned (no lock): staging ring + inflight window
        self._buffers = [empty_batch(max_bucket)
                         for _ in range(inflight + 1)]
        self._free_bufs: List[int] = list(range(len(self._buffers)))
        self._stage_buf: Optional[int] = None
        self._staged_rows = 0
        self._staged_slices: List[_Slice] = []
        self._stage_deadline = 0.0
        self._stage_now: Optional[int] = None
        self._inflight: deque = deque()
        self._current: Optional[_Sub] = None   # popped, mid-_ingest

        # stats (worker-owned except drops/submitted)
        self.submitted = 0
        self.admission_drops = 0
        self.dispatched_batches = 0
        self.completed_batches = 0
        self.dispatch_faults = 0
        self.dispatch_errors = 0
        self.flush_reasons: Dict[str, int] = {
            "direct": 0, "full": 0, "deadline": 0, "drain": 0}
        self._fill_rows = 0
        self._bucket_rows = 0

        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-worker")
        self._worker.start()

    # -- producer side -------------------------------------------------------
    def submit(self, batch: Dict[str, np.ndarray],
               now: Optional[int] = None,
               timeout: Optional[float] = None) -> Ticket:
        """Admit one batch (records layout, ``valid``-masked). Returns a
        :class:`Ticket` immediately; with ``admission="drop"`` (or a blocked
        admission that times out) the ticket comes back already rejected
        with :class:`PipelineDrop` — check ``ticket.dropped``.

        The caller must not mutate ``batch`` until the ticket resolves (the
        staging copy happens on the worker; a direct-dispatch batch is read
        by the flow log at finalize time)."""
        valid = np.asarray(batch["valid"])
        n_valid = int(valid.sum())
        if n_valid > self._max_bucket:
            raise ValueError(
                f"submission has {n_valid} valid rows > max_bucket "
                f"{self._max_bucket}; split it or raise batch_size")
        ticket = Ticket(n_rows=int(valid.shape[0]), n_valid=n_valid)
        # the sampling decision is made once per submission and rides the
        # ticket; unsampled submissions pay exactly one counter draw here
        ticket.trace_id = self.tracer.maybe_sample()
        deadline = time.monotonic() + (
            self._block_timeout_s if timeout is None else timeout)
        with self._lock:
            if self._closing or self._closed:
                raise PipelineClosed("pipeline is closed")
            while len(self._queue) >= self._queue_max:
                remaining = deadline - time.monotonic()
                if self._admission == "drop" or remaining <= 0:
                    self.admission_drops += 1
                    self.metrics.inc_counter("pipeline_admission_drops_total")
                    ticket._reject(PipelineDrop(
                        f"queue full ({self._queue_max} batches); "
                        f"admission={self._admission}"))
                    return ticket
                self._cond.wait(min(remaining, 0.05))
                if self._closing or self._closed:
                    raise PipelineClosed("pipeline closed while blocked "
                                         "at admission")
            ticket.seq = self._next_seq
            self._next_seq += 1
            self._queue.append(_Sub(ticket, batch, now))
            self.submitted += 1
            self._outstanding += 1
            self.metrics.set_gauge("pipeline_queue_depth", len(self._queue))
            self._cond.notify_all()
        return ticket

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted submission so far has resolved
        (flushes any staged microbatch immediately — ``drain`` flush
        reason). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            self._drain_req += 1
            self._cond.notify_all()
            try:
                while self._outstanding > 0:
                    remaining = None if deadline is None else \
                        deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._cond.wait(remaining if remaining is None
                                    else min(remaining, 0.1))
            finally:
                self._drain_req -= 1
                self._cond.notify_all()
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        """Clean shutdown: stop admitting, process everything already
        queued/staged/in flight, then stop the worker. Idempotent."""
        with self._lock:
            if self._closed and not self._worker.is_alive():
                return
            self._closing = True
            self._cond.notify_all()
        self._worker.join(timeout)
        with self._lock:
            self._closed = True
            if self._worker.is_alive():
                log.warning("pipeline worker did not stop within %ss",
                            timeout)

    # -- runtime-tunable knobs (observe/autotune.py consumer) -----------------
    @property
    def flush_ms(self) -> float:
        return self._flush_s * 1e3

    @property
    def min_bucket(self) -> int:
        return self._min_bucket

    @property
    def max_bucket(self) -> int:
        return self._max_bucket

    def set_flush_ms(self, flush_ms: float) -> None:
        """Retarget the microbatch coalesce deadline (applies to the next
        staged submission; an already-armed deadline keeps its anchor)."""
        if flush_ms <= 0:
            raise ValueError("flush_ms must be > 0")
        with self._lock:
            self._flush_s = flush_ms / 1e3
            self._cond.notify_all()     # re-evaluate a parked deadline wait

    def set_min_bucket(self, min_bucket: int) -> None:
        """Move the smallest dispatch shape (the bucket-set floor)."""
        if min_bucket & (min_bucket - 1) or \
                not 0 < min_bucket <= self._max_bucket:
            raise ValueError("min_bucket must be a power of two "
                             "<= max_bucket")
        with self._lock:
            self._min_bucket = min_bucket

    # -- introspection --------------------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            queue_depth = len(self._queue)
            outstanding = self._outstanding
        qw = self.metrics.histograms.get("pipeline_queue_wait_seconds")
        return {
            "submitted": self.submitted,
            "outstanding": outstanding,
            "queue_depth": queue_depth,
            "staged_rows": self._staged_rows,
            "inflight": len(self._inflight),
            "admission_drops": self.admission_drops,
            "dispatched_batches": self.dispatched_batches,
            "completed_batches": self.completed_batches,
            "dispatch_faults": self.dispatch_faults,
            "dispatch_errors": self.dispatch_errors,
            "flush_reasons": dict(self.flush_reasons),
            "fill_rows": self._fill_rows,
            "bucket_rows": self._bucket_rows,
            "flush_ms": self.flush_ms,
            "min_bucket": self._min_bucket,
            "fill_ratio_avg": round(self._fill_rows
                                    / max(1, self._bucket_rows), 4),
            "queue_wait_p50_ms": round(qw.quantile(0.5) * 1e3, 3)
            if qw else 0.0,
            "queue_wait_p99_ms": round(qw.quantile(0.99) * 1e3, 3)
            if qw else 0.0,
            "closed": self._closed or self._closing,
        }

    # -- worker side ----------------------------------------------------------
    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException:            # noqa: BLE001 — never strand tickets
            log.exception("pipeline worker died; rejecting outstanding work")
            exc = PipelineError("pipeline worker crashed")
            with self._lock:
                # flip closed under the lock FIRST so no producer can admit
                # a ticket into the dead queue after we sweep it
                self._closing = True
                self._closed = True
                pending = [s.ticket for s in self._queue]
                self._queue.clear()
            if self._current is not None:    # the sub that was mid-_ingest
                pending.append(self._current.ticket)
                self._current = None
            pending.extend(sl.ticket for sl in self._staged_slices)
            self._staged_slices = []
            for inf in self._inflight:
                pending.extend(sl.ticket for sl in inf.slices)
            self._inflight.clear()
            rejected = 0
            for t in pending:
                if not t.done():             # also dedups double-listed ones
                    t._reject(exc)
                    rejected += 1
            with self._lock:
                self._outstanding -= rejected
                self._cond.notify_all()

    def _run_inner(self) -> None:
        while True:
            sub = None
            action = None
            with self._lock:
                while True:
                    if self._queue:
                        sub = self._queue.popleft()
                        depth = len(self._queue)
                        self.metrics.set_gauge("pipeline_queue_depth", depth)
                        if depth >= self._queue_max - 1:
                            self._cond.notify_all()   # wake blocked producers
                        action = "ingest"
                        break
                    if self._staged_slices and (
                            self._drain_req or self._closing
                            or time.monotonic() >= self._stage_deadline):
                        action = ("drain" if (self._drain_req
                                              or self._closing)
                                  else "deadline")
                        break
                    if self._inflight:
                        # idle with work in flight: finalize eagerly so a
                        # lone submission never waits for a successor
                        action = "finalize"
                        break
                    if self._closing:
                        return
                    wait = None
                    if self._staged_slices:
                        wait = max(0.0, self._stage_deadline
                                   - time.monotonic())
                    self._cond.wait(wait)
            if action == "ingest":
                self._current = sub
                self._ingest(sub)
                self._current = None
            elif action == "finalize":
                self._finalize_oldest()
            else:
                self._flush(action)

    def _ingest(self, sub: _Sub) -> None:
        t = sub.ticket
        m = t.n_valid
        if m == 0:
            # nothing to classify: resolve without a device round trip
            wait = time.monotonic() - t.submitted_mono
            self.metrics.histogram("pipeline_queue_wait_seconds").observe(
                wait)
            self.tracer.record(t.trace_id, "pipeline.admission",
                               t.submitted_mono, wait)
            t._resolve(_zero_out(t.n_rows))
            self._resolved(1)
            return
        rows = t.n_rows
        if (self._staged_rows == 0
                and self._min_bucket <= rows <= self._max_bucket
                and rows & (rows - 1) == 0):
            # already bucket-shaped: zero-copy direct dispatch
            self._dispatch(sub.batch, sub.now,
                           [_Slice(t, None, 0)], rows, m, "direct", None)
            return
        if self._staged_rows + m > self._max_bucket:
            self._flush("full")
        if self._stage_buf is None:
            self._stage_buf = self._acquire_buffer()
            # the deadline is anchored to the oldest rider's SUBMIT time so
            # backlogged submissions flush immediately instead of waiting
            # another full window
            self._stage_deadline = t.submitted_mono + self._flush_s
            self._stage_now = None
        valid_idx = np.nonzero(np.asarray(sub.batch["valid"]))[0]
        buf = self._buffers[self._stage_buf]
        pos = self._staged_rows
        with self.tracer.span(t.trace_id, "pipeline.microbatch", rows=m):
            for k, col in buf.items():
                col[pos:pos + m] = np.asarray(sub.batch[k])[valid_idx]
        if self._stage_now is None:
            self._stage_now = sub.now
        self._staged_slices.append(_Slice(t, valid_idx, pos))
        self._staged_rows += m
        if self._staged_rows >= self._max_bucket:
            self._flush("full")

    def _flush(self, reason: str) -> None:
        if not self._staged_slices:
            return
        buf_idx = self._stage_buf
        buf = self._buffers[buf_idx]
        rows = self._staged_rows
        bucket = max(self._min_bucket, _next_pow2(rows))
        buf["valid"][rows:bucket] = False    # reused buffer: mask stale rows
        view = {k: col[:bucket] for k, col in buf.items()}
        slices = self._staged_slices
        now = self._stage_now
        self._stage_buf = None
        self._staged_rows = 0
        self._staged_slices = []
        self._stage_now = None
        self._dispatch(view, now, slices, bucket, rows, reason, buf_idx)

    def _dispatch(self, batch: Dict[str, np.ndarray], now: Optional[int],
                  slices: List[_Slice], bucket_rows: int, n_valid: int,
                  reason: str, buf_idx: Optional[int]) -> None:
        if now is None:
            now = int(time.time())
        self.flush_reasons[reason] = self.flush_reasons.get(reason, 0) + 1
        self.metrics.inc_counter(f"pipeline_flush_{reason}_total")
        self._fill_rows += n_valid
        self._bucket_rows += bucket_rows
        self.metrics.set_gauge("pipeline_fill_ratio",
                               round(n_valid / bucket_rows, 4))
        t0 = time.monotonic()
        qw = self.metrics.histogram("pipeline_queue_wait_seconds")
        for sl in slices:
            qw.observe(t0 - sl.ticket.submitted_mono)
            self.tracer.record(sl.ticket.trace_id, "pipeline.admission",
                               sl.ticket.submitted_mono,
                               t0 - sl.ticket.submitted_mono)
        # the batch-level spans ride the first sampled rider's trace; the
        # trace context makes the datapath's pack/transfer/compute split
        # attach to the same trace id across the backend boundary
        tid = next((sl.ticket.trace_id for sl in slices
                    if sl.ticket.trace_id is not None), None)

        attempts = 0
        while True:
            try:
                FAULTS.fire("pipeline.dispatch")
                with self.tracer.context(tid), \
                        self.tracer.span(tid, "pipeline.dispatch",
                                         bucket=bucket_rows,
                                         n_valid=n_valid, reason=reason):
                    finalize = self._dispatch_fn(batch, now)
                break
            except FaultInjected as e:
                self.dispatch_faults += 1
                self.metrics.inc_counter("pipeline_dispatch_faults_total")
                attempts += 1
                cap = (MAX_DISPATCH_RETRIES_CLOSING if self._closing
                       else MAX_DISPATCH_RETRIES)
                if attempts >= cap:
                    self._reject_slices(slices, e, buf_idx)
                    return
                time.sleep(min(0.05, 0.0005 * (1 << min(attempts, 7))))
            except Exception as e:   # noqa: BLE001 — supervised degradation
                self.dispatch_errors += 1
                self.metrics.inc_counter("pipeline_dispatch_errors_total")
                log.warning("pipeline dispatch failed, rejecting %d "
                            "submission(s): %s", len(slices), e)
                self._reject_slices(slices, e, buf_idx)
                return
        self.dispatched_batches += 1
        self._inflight.append(_Inflight(finalize, slices, t0, buf_idx))
        self.metrics.set_gauge("pipeline_inflight", len(self._inflight))
        # keep at most ``inflight`` batches genuinely in flight; the ring
        # has inflight+1 staging buffers so the next microbatch can stage
        # while the window is full
        while len(self._inflight) > self._inflight_max:
            self._finalize_oldest()

    def _finalize_oldest(self) -> None:
        if not self._inflight:
            return
        inf: _Inflight = self._inflight.popleft()
        tid = next((sl.ticket.trace_id for sl in inf.slices
                    if sl.ticket.trace_id is not None), None)
        try:
            with self.tracer.context(tid), \
                    self.tracer.span(tid, "pipeline.finalize"):
                out = inf.finalize()
        except Exception as e:   # noqa: BLE001
            self.dispatch_errors += 1
            self.metrics.inc_counter("pipeline_dispatch_errors_total")
            log.warning("pipeline finalize failed, rejecting %d "
                        "submission(s): %s", len(inf.slices), e)
            self._reject_slices(inf.slices, e, inf.buf_idx)
            return
        self.metrics.histogram("pipeline_batch_latency_seconds").observe(
            time.monotonic() - inf.t_dispatch)
        for sl in inf.slices:
            if sl.valid_idx is None:        # direct: geometry already matches
                sl.ticket._resolve(out)
                continue
            n = len(sl.valid_idx)
            tout = _zero_out(sl.ticket.n_rows)
            for k, arr in out.items():
                if k not in tout:
                    tout[k] = np.zeros((sl.ticket.n_rows,) + arr.shape[1:],
                                       dtype=arr.dtype)
                tout[k][sl.valid_idx] = arr[sl.dst_start:sl.dst_start + n]
            sl.ticket._resolve(tout)
        self.completed_batches += 1
        self._recycle(inf.buf_idx)
        self.metrics.set_gauge("pipeline_inflight", len(self._inflight))
        self._resolved(len(inf.slices))

    # -- small helpers ---------------------------------------------------------
    def _acquire_buffer(self) -> int:
        while not self._free_bufs:
            self._finalize_oldest()
        return self._free_bufs.pop()

    def _recycle(self, buf_idx: Optional[int]) -> None:
        if buf_idx is not None:
            self._free_bufs.append(buf_idx)

    def _reject_slices(self, slices: Sequence[_Slice], exc: BaseException,
                       buf_idx: Optional[int]) -> None:
        wrapped = exc if isinstance(exc, PipelineError) else \
            PipelineError(f"dispatch failed: {type(exc).__name__}: {exc}")
        wrapped.__cause__ = exc
        for sl in slices:
            sl.ticket._reject(wrapped)
        self._recycle(buf_idx)
        self._resolved(len(slices))

    def _resolved(self, n: int) -> None:
        with self._lock:
            self._outstanding -= n
            # drain waiters only care about reaching zero; producers are
            # woken by the queue pop — skip the per-batch thundering herd
            if self._outstanding == 0 or self._closing:
                self._cond.notify_all()
