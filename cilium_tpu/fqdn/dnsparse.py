"""DNS response decoding over harvested payload columns (upstream:
pkg/fqdn/dnsproxy's miekg/dns parse, rebuilt columnar).

The batch entry point is :func:`decode_batch`: a vectorized numpy header
pre-screen over every candidate row (QR/opcode/TC/rcode/counts read as
big-endian u16 lanes — the storm-rate common case of "not a learnable
answer" never enters Python), then a per-row walk only for rows that
survive. The walk is compression-pointer-safe: pointers may only jump
BACKWARD (RFC 1035 compliant encoders always do; a forward pointer is
how crafted frames build loops), jump count and assembled name length
are bounded, and every length field is checked against the frame edge.

Malformedness is a deliberate three-way split:
  * not-a-learnable-response (a query, TC set, non-zero rcode, zero
    answers) — valid DNS, silently skipped;
  * zero-length payload — no DNS was harvested for the row, skipped;
  * anything that violates the wire grammar (truncated header, label or
    rdata running off the frame, pointer loops/forward pointers,
    over-long names, non-ascii labels) — counted malformed, learned
    nothing. The proxy folds that count into
    ``fqdn_parse_errors_total``; the reply itself is never dropped
    (fail-open — see fqdn/proxy.py).

:func:`encode_response` is the matching wire builder (tests, the cfg9
churn driver, and the dns-poison chaos phase synthesize answers with
it), including the 0xC00C question-pointer compression real resolvers
emit — so the decoder's pointer path is exercised by every synthetic
frame, not just hand-built edge cases.
"""

from __future__ import annotations

import ipaddress
from typing import List, Optional, Sequence, Tuple

import numpy as np

HEADER_LEN = 12
TYPE_A = 1
TYPE_CNAME = 5
TYPE_AAAA = 28
CLASS_IN = 1
MAX_NAME_LEN = 255          # RFC 1035 §2.3.4 total name octets
MAX_LABEL_LEN = 63
MAX_PTR_JUMPS = 16          # backward-only already bounds loops; belt+braces


def _read_name(buf: bytes, off: int) -> Tuple[str, int]:
    """Walk one (possibly compressed) name starting at ``off``.

    Returns ``(name, next_off)`` where ``next_off`` is the offset just
    past the name IN THE ORIGINAL STREAM (pointers don't advance it).
    Raises ValueError on any grammar violation.
    """
    n = len(buf)
    labels: List[str] = []
    end: Optional[int] = None     # stream offset after the name
    jumps = 0
    total = 0
    while True:
        if off >= n:
            raise ValueError("name runs off frame")
        b = buf[off]
        if b == 0:
            if end is None:
                end = off + 1
            break
        if b & 0xC0 == 0xC0:
            if off + 1 >= n:
                raise ValueError("truncated compression pointer")
            ptr = ((b & 0x3F) << 8) | buf[off + 1]
            if end is None:
                end = off + 2
            if ptr >= off:
                # forward/self pointers are how crafted frames loop; a
                # compliant encoder only ever points at earlier bytes
                raise ValueError("non-backward compression pointer")
            jumps += 1
            if jumps > MAX_PTR_JUMPS:
                raise ValueError("compression pointer chain too long")
            off = ptr
            continue
        if b & 0xC0:
            raise ValueError("reserved label type")
        if b > MAX_LABEL_LEN:
            raise ValueError("label too long")
        if off + 1 + b > n:
            raise ValueError("label runs off frame")
        total += b + 1
        if total > MAX_NAME_LEN:
            raise ValueError("name too long")
        labels.append(buf[off + 1:off + 1 + b].decode("ascii"))
        off += 1 + b
    return ".".join(labels), end


def _skip_question(buf: bytes, off: int) -> int:
    _, off = _read_name(buf, off)
    if off + 4 > len(buf):
        raise ValueError("question runs off frame")
    return off + 4


def parse_frame(buf: bytes) -> Optional[Tuple[str, List[str], int]]:
    """Decode one DNS response frame → ``(qname, ips, min_ttl)``.

    Returns None for valid-but-unlearnable frames (non-response, TC,
    rcode != 0, no A/AAAA answers); raises ValueError on malformed
    frames. Answers attach to the FIRST question's qname regardless of
    CNAME indirection — upstream learns the name the workload ASKED
    for, not the alias chain's tail (pkg/fqdn: lookups are keyed by the
    selector-matched name).
    """
    if not isinstance(buf, (bytes, bytearray)):
        buf = bytes(buf)          # accept uint8 ndarray rows directly
    if len(buf) < HEADER_LEN:
        raise ValueError("frame shorter than DNS header")
    flags = int.from_bytes(buf[2:4], "big")
    qd = int.from_bytes(buf[4:6], "big")
    an = int.from_bytes(buf[6:8], "big")
    qr = (flags >> 15) & 1
    opcode = (flags >> 11) & 0xF
    tc = (flags >> 9) & 1
    rcode = flags & 0xF
    if qr != 1 or opcode != 0 or tc or rcode != 0 or qd < 1 or an < 1:
        return None
    qname, off = _read_name(buf, HEADER_LEN)
    if not qname:
        raise ValueError("empty qname")
    if off + 4 > len(buf):
        raise ValueError("question runs off frame")
    off += 4
    for _ in range(qd - 1):
        off = _skip_question(buf, off)
    ips: List[str] = []
    min_ttl: Optional[int] = None
    for _ in range(an):
        _, off = _read_name(buf, off)
        if off + 10 > len(buf):
            raise ValueError("answer header runs off frame")
        rtype = int.from_bytes(buf[off:off + 2], "big")
        rclass = int.from_bytes(buf[off + 2:off + 4], "big")
        ttl = int.from_bytes(buf[off + 4:off + 8], "big")
        rdlen = int.from_bytes(buf[off + 8:off + 10], "big")
        off += 10
        if off + rdlen > len(buf):
            raise ValueError("rdata runs off frame")
        if rclass == CLASS_IN and rtype == TYPE_A:
            if rdlen != 4:
                raise ValueError("A rdata length != 4")
            ips.append(str(ipaddress.IPv4Address(buf[off:off + 4])))
            min_ttl = ttl if min_ttl is None else min(min_ttl, ttl)
        elif rclass == CLASS_IN and rtype == TYPE_AAAA:
            if rdlen != 16:
                raise ValueError("AAAA rdata length != 16")
            ips.append(str(ipaddress.IPv6Address(buf[off:off + 16])))
            min_ttl = ttl if min_ttl is None else min(min_ttl, ttl)
        # CNAME/other rrtypes: legal, contribute no addresses
        off += rdlen
    if not ips:
        return None
    return qname, ips, int(min_ttl or 0)


def decode_batch(payload: np.ndarray, lengths: np.ndarray,
                 rows: Optional[Sequence[int]] = None,
                 ) -> Tuple[List[Tuple[int, str, List[str], int]], int]:
    """Decode DNS responses out of a ``[batch, W] uint8`` payload column.

    ``lengths`` is the per-row harvested byte count (0 = no payload).
    ``rows`` optionally restricts which rows are candidates (the proxy
    passes its verdict/port selection). Returns ``(results, malformed)``
    where results is a list of ``(row, qname, ips, min_ttl)`` for
    learnable answers and ``malformed`` counts grammar-violating frames.
    """
    payload = np.asarray(payload)
    lengths = np.asarray(lengths)
    if rows is None:
        idx = np.nonzero(lengths > 0)[0]
    else:
        idx = np.asarray(rows, dtype=np.int64)
        idx = idx[lengths[idx] > 0]
    results: List[Tuple[int, str, List[str], int]] = []
    if idx.size == 0:
        return results, 0
    width = payload.shape[1]
    clipped = np.minimum(lengths[idx], width)
    # vectorized header screen: rows too short for a header are malformed
    # outright; the rest are screened on QR/opcode/TC/rcode/counts so
    # only plausibly-learnable responses pay the per-row Python walk
    short = clipped < HEADER_LEN
    malformed = int(short.sum())
    cand = idx[~short]
    if cand.size == 0:
        return results, malformed
    hdr = payload[cand, :HEADER_LEN].astype(np.uint32)
    flags = (hdr[:, 2] << 8) | hdr[:, 3]
    qd = (hdr[:, 4] << 8) | hdr[:, 5]
    an = (hdr[:, 6] << 8) | hdr[:, 7]
    learnable = (((flags >> 15) & 1) == 1) \
        & (((flags >> 11) & 0xF) == 0) \
        & (((flags >> 9) & 1) == 0) \
        & ((flags & 0xF) == 0) \
        & (qd >= 1) & (an >= 1)
    for r in cand[learnable]:
        buf = payload[r, :int(min(lengths[r], width))].tobytes()
        try:
            parsed = parse_frame(buf)
        except ValueError:
            malformed += 1
            continue
        if parsed is not None:
            qname, ips, ttl = parsed
            results.append((int(r), qname, ips, ttl))
    return results, malformed


def encode_name(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        raw = label.encode("ascii")
        if not 0 < len(raw) <= MAX_LABEL_LEN:
            raise ValueError(f"bad label in {name!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    if len(out) > MAX_NAME_LEN:
        raise ValueError(f"name too long: {name!r}")
    return bytes(out)


def encode_response(qname: str, ips: Sequence[str], ttl: int = 60, *,
                    txid: int = 0, rcode: int = 0,
                    compress: bool = True) -> bytes:
    """Build one DNS response frame (the churn driver / test fixture).

    With ``compress`` (default) answer owner names are the 0xC00C
    pointer at the question — the encoding real resolvers emit, so
    round-tripping through :func:`parse_frame` exercises the pointer
    walk. ``rcode`` lets tests build NXDOMAIN-class valid-but-
    unlearnable frames.
    """
    addrs = [ipaddress.ip_address(ip) for ip in ips]
    flags = 0x8180 | (rcode & 0xF)          # QR|RD|RA response
    out = bytearray()
    out += int(txid).to_bytes(2, "big")
    out += flags.to_bytes(2, "big")
    out += (1).to_bytes(2, "big")           # qdcount
    out += len(addrs).to_bytes(2, "big")    # ancount
    out += (0).to_bytes(4, "big")           # ns/ar
    qtype = TYPE_AAAA if addrs and addrs[0].version == 6 else TYPE_A
    wire_name = encode_name(qname)
    out += wire_name
    out += qtype.to_bytes(2, "big") + CLASS_IN.to_bytes(2, "big")
    for a in addrs:
        if compress:
            out += b"\xc0\x0c"              # pointer to the question name
        else:
            out += wire_name
        rtype = TYPE_AAAA if a.version == 6 else TYPE_A
        out += rtype.to_bytes(2, "big") + CLASS_IN.to_bytes(2, "big")
        out += int(ttl).to_bytes(4, "big")
        rdata = a.packed
        out += len(rdata).to_bytes(2, "big") + rdata
    return bytes(out)
