"""In-band DNS plane (pkg/fqdn dataplane analog, ISSUE 18).

The serving-path half of FQDN policy: ``dnsparse`` decodes harvested DNS
response payloads (vectorized pre-screen + compression-pointer-safe name
walk), ``proxy`` taps the feeder's verdict-apply path for rows whose
verdict carries the DNS L7 redirect class and feeds parsed answers to
``model/fqdn.FQDNCache.observe`` — closing the loop ROADMAP item 1b named:
traffic-observed names drive ``toFQDNs`` identities through the delta
patch path.
"""

from cilium_tpu.fqdn.dnsparse import decode_batch, encode_response, \
    parse_frame
from cilium_tpu.fqdn.proxy import DNSProxy

__all__ = ["decode_batch", "encode_response", "parse_frame", "DNSProxy"]
