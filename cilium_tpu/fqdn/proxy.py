"""The DNS learning tap on the feeder's verdict-apply path (upstream:
pkg/fqdn/dnsproxy's port-53 interception, rebuilt as a batch observer).

Upstream runs an inline proxy: toFQDNs rules compile an implicit
port-53 L7 redirect, the proxy terminates the flow, forwards the query,
and LEARNS from the response before handing it back. This repo's
datapath is batch/columnar — the analog is a tap, not a terminator:
rows whose verdict carries the DNS L7 redirect class
(``VERDICT_REDIRECT``, UDP port 53) and whose harvest captured response
payload bytes (``_dns_payload``/``_dns_len`` poll-buffer columns) are
decoded and fed to ``FQDNCache.observe``.

The FAIL-OPEN contract is the load-bearing part: the tap runs AFTER the
verdict is computed and touches neither the verdict arrays nor the
apply call. A broken parser (the ``fqdn.parse`` fault point, malformed
storms, any bug in this file) loses LEARNING — counted in
``fqdn_parse_errors_total`` — never the DNS reply itself. Upstream
made the same call: a dnsproxy error path that dropped replies would
turn a parser bug into a cluster-wide resolution outage.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from cilium_tpu.fqdn.dnsparse import decode_batch
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C

DNS_PORT = 53


class DNSProxy:
    """Batch DNS-response observer feeding an ``FQDNCache``.

    ``observe_batch(buf, out)`` never raises and never mutates ``buf``
    or ``out`` — the caller's verdict-apply path is invariant to
    anything that happens in here.
    """

    def __init__(self, cache, *, metrics=None, min_ttl: int = 0,
                 port: int = DNS_PORT, payload_width: int = 512):
        self.cache = cache
        self.metrics = metrics
        self.min_ttl = int(min_ttl)
        self.port = int(port)
        # poll-buffer ``_dns_payload`` column width the feeder allocates;
        # longer responses are truncated at harvest (truncation shows up
        # as a malformed frame, not a crash)
        self.payload_width = int(payload_width)
        self._lock = threading.Lock()
        self.observed_total = 0       # learnable answers fed to the cache
        self.parse_errors_total = 0   # malformed frames + parser faults
        self.frames_total = 0         # DNS-redirect rows inspected

    def observe_batch(self, buf: Dict[str, np.ndarray], out) -> int:
        """Learn from one applied batch; returns answers observed."""
        try:
            payload = buf.get("_dns_payload")
            lens = buf.get("_dns_len")
            if payload is None or lens is None or not isinstance(out, dict):
                return 0
            redirect = out.get("redirect")
            if redirect is None:
                return 0
            n = min(len(lens), len(np.asarray(redirect)))
            sel = np.asarray(buf["valid"][:n], dtype=bool) \
                & np.asarray(redirect[:n], dtype=bool) \
                & (np.asarray(buf["proto"][:n]) == C.PROTO_UDP) \
                & ((np.asarray(buf["sport"][:n]) == self.port)
                   | (np.asarray(buf["dport"][:n]) == self.port)) \
                & (np.asarray(lens[:n]) > 0)
            rows = np.nonzero(sel)[0]
            if rows.size == 0:
                return 0
        except Exception:   # noqa: BLE001 — selection itself fail-opens
            self._count_errors(1)
            return 0
        try:
            # the chaos-pinned fault point: a "broken parser" costs
            # learning for this batch's DNS rows, nothing else
            FAULTS.fire("fqdn.parse")
            results, malformed = decode_batch(payload, lens, rows)
        except Exception:   # noqa: BLE001 — incl. FaultInjected
            self._count_frames(int(rows.size))
            self._count_errors(int(rows.size))
            return 0
        self._count_frames(int(rows.size))
        if malformed:
            self._count_errors(malformed)
        learned = 0
        for _row, qname, ips, ttl in results:
            try:
                now = int(self.cache.clock())
                self.cache.observe(qname, ips,
                                   max(int(ttl), self.min_ttl), now)
                learned += len(ips)
            except Exception:   # noqa: BLE001
                self._count_errors(1)
        if learned:
            with self._lock:
                self.observed_total += learned
            if self.metrics is not None:
                self.metrics.inc_counter("fqdn_observed_total", learned)
        return learned

    def _count_frames(self, n: int) -> None:
        with self._lock:
            self.frames_total += n

    def _count_errors(self, n: int) -> None:
        with self._lock:
            self.parse_errors_total += n
        if self.metrics is not None:
            try:
                self.metrics.inc_counter("fqdn_parse_errors_total", n)
            except Exception:   # noqa: BLE001
                pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "frames": self.frames_total,
                "observed": self.observed_total,
                "parse_errors": self.parse_errors_total,
            }
