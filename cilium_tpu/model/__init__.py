"""Data model: labels, selectors, rules, identities, ipcache.

Analog of upstream ``pkg/labels``, ``pkg/policy/api``, ``pkg/identity``,
``pkg/ipcache`` (paths per SURVEY.md §2 — reconstructed, reference mount empty).
"""

from cilium_tpu.model.labels import Label, Labels, parse_label
from cilium_tpu.model.selectors import EndpointSelector
from cilium_tpu.model.rules import Rule, parse_rule, parse_rules
from cilium_tpu.model.identity import Identity, IdentityAllocator
from cilium_tpu.model.ipcache import IPCache

__all__ = [
    "Label", "Labels", "parse_label",
    "EndpointSelector",
    "Rule", "parse_rule", "parse_rules",
    "Identity", "IdentityAllocator",
    "IPCache",
]
