"""Local endpoint model (thin analog of upstream ``pkg/endpoint``).

An endpoint is one local workload interface (pod). It owns a security
identity (from its labels), a set of IPs (mirrored into the ipcache), and a
per-endpoint policy image slot in the compiled snapshot. Lifecycle/regen
orchestration lives in ``cilium_tpu/runtime``; this module is just the data.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from cilium_tpu.model.labels import Labels


@dataclass
class Endpoint:
    ep_id: int                       # local endpoint id (small int, dense)
    labels: Labels
    ips: Tuple[str, ...] = ()
    identity_id: int = 0             # filled by the allocator at registration
    # Per-endpoint enforcement override (None → follow daemon config), the
    # analog of upstream's per-endpoint PolicyEnforcement option.
    enforcement: Optional[str] = None
    policy_revision: int = 0         # last repository revision realized on device

    def __post_init__(self):
        if not (0 <= self.ep_id):
            raise ValueError("endpoint id must be non-negative")
