"""FQDN policy support (analog of upstream ``pkg/fqdn``): a DNS cache
mapping names → learned IPs with TTLs, consumed by ``toFQDNs`` rules.

Upstream learns names from its DNS proxy (it sits on port 53 via an L7
redirect and observes responses); this framework exposes the same cache
with a programmatic ``observe()`` feed — the AF_XDP shim or any resolver
integration calls it with (name, ips, ttl). Learned IPs materialize as
CIDR identities exactly like ``toCIDR`` peers, so the datapath needs no
FQDN awareness at all (same as upstream, where toFQDNs compiles down to
ipcache entries + selector identities).

Pattern semantics mirror upstream's ``matchPattern``: ``*`` matches any
run of DNS-label characters ``[-a-zA-Z0-9.]*`` (yes, dots too — upstream's
matchpattern.go converts ``*`` to ``.*`` over the whole name); matching is
case-insensitive on normalized names (lowercase, trailing dot stripped).
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Sequence, Tuple


def normalize_name(name: str) -> str:
    return name.strip().lower().rstrip(".")


@dataclass(frozen=True)
class FQDNSelector:
    """One toFQDNs entry: matchName (exact) or matchPattern (glob)."""
    match_name: str = ""
    match_pattern: str = ""

    def __post_init__(self):
        if bool(self.match_name) == bool(self.match_pattern):
            raise ValueError(
                "toFQDNs entry needs exactly one of matchName/matchPattern")
        object.__setattr__(self, "match_name",
                           normalize_name(self.match_name))
        object.__setattr__(self, "match_pattern",
                           normalize_name(self.match_pattern))
        if self.match_pattern:
            pat = "".join(
                "[-a-zA-Z0-9.]*" if ch == "*" else re.escape(ch)
                for ch in self.match_pattern)
            object.__setattr__(self, "_compiled", re.compile(f"^{pat}$"))

    def matches(self, name: str) -> bool:
        name = normalize_name(name)
        if self.match_name:
            return name == self.match_name
        return self._compiled.match(name) is not None


class FQDNCache:
    """name → {ip: expiry}. Thread-safe; observers fire on any change that
    can affect policy (new IP learned, IP expired/flushed)."""

    def __init__(self, min_ttl: int = 0, clock: Callable[[], float] = None,
                 max_names: int = 0, max_ips_per_name: int = 0):
        self._lock = threading.RLock()
        self._entries: Dict[str, Dict[str, int]] = {}
        self._observers: List[Callable[[], None]] = []
        # upstream tofqdns-min-ttl: clamp tiny TTLs so churn-happy records
        # don't thrash policy recomputation
        self.min_ttl = min_ttl
        # bounds (upstream tofqdns-endpoint-max-ip-per-hostname /
        # max-deferred-connection-deletes class of knobs): a spoofed-
        # response storm must not grow the dict — and through
        # materialization, the identity space — without limit. 0 =
        # unbounded. Eviction is oldest-expiry-first: the entry closest
        # to dying anyway is the one a bound sacrifices.
        self.max_names = int(max_names)
        self.max_ips_per_name = int(max_ips_per_name)
        self._count = 0          # total live IP entries (incremental)
        self._high_water = 0     # peak _count (ResourceLedger row)
        self._evictions = 0      # bound-forced removals (not TTL expiry)
        # clock used when callers (rule materialization) don't pass ``now``;
        # tests override with a synthetic clock
        import time
        self.clock = clock or time.time

    def add_observer(self, obs: Callable[[], None]) -> None:
        self._observers.append(obs)

    def _notify(self):
        for obs in list(self._observers):
            obs()

    def observe(self, name: str, ips: Sequence[str], ttl: int,
                now: int) -> bool:
        """Record a DNS answer. Returns True (and notifies) iff a new IP was
        learned — TTL refreshes alone don't need a policy recompute."""
        import ipaddress
        valid_ips = []
        for ip in ips:
            try:
                valid_ips.append(str(ipaddress.ip_address(ip)))
            except ValueError:
                # a garbage answer must not poison the cache: materialization
                # would crash on it inside the change observer and wedge all
                # toFQDNs policy until the TTL expired
                continue
        if not valid_ips:
            return False  # NXDOMAIN/empty answers must not create ghost names
        name = normalize_name(name)
        expiry = now + max(int(ttl), self.min_ttl)
        changed = False
        with self._lock:
            is_new_name = name not in self._entries
            ent = self._entries.setdefault(name, {})
            for ip in valid_ips:
                prev = ent.get(ip)
                if prev is None or prev <= now:
                    # new OR expired-but-not-yet-GC'd: either way the
                    # materialized policy may lack this IP → recompute
                    changed = True
                if prev is None:
                    self._count += 1
                ent[ip] = max(prev or 0, expiry)
            # per-name IP cap: shed oldest-expiry IPs past the bound
            if self.max_ips_per_name > 0:
                while len(ent) > self.max_ips_per_name:
                    victim = min(ent, key=ent.get)
                    del ent[victim]
                    self._count -= 1
                    self._evictions += 1
                    changed = True
            # name cap: shed the name whose LAST IP expires soonest
            # (never the name just observed — it carries the freshest TTL)
            if is_new_name and self.max_names > 0:
                while len(self._entries) > self.max_names:
                    victim = min(
                        (n for n in self._entries if n != name),
                        key=lambda n: max(self._entries[n].values()),
                        default=None)
                    if victim is None:
                        break
                    dead = self._entries.pop(victim)
                    self._count -= len(dead)
                    self._evictions += len(dead)
                    changed = True
            if self._count > self._high_water:
                self._high_water = self._count
        if changed:
            self._notify()
        return changed

    def expire(self, now: int) -> int:
        """GC expired IPs (upstream: fqdn cache GC controller). Notifies if
        anything was removed (policy must drop the identities)."""
        removed = 0
        with self._lock:
            for name in list(self._entries):
                ent = self._entries[name]
                dead = [ip for ip, exp in ent.items() if exp <= now]
                for ip in dead:
                    del ent[ip]
                removed += len(dead)
                if not ent:
                    del self._entries[name]
            self._count -= removed
        if removed:
            self._notify()
        return removed

    def stats(self, now: int = None) -> Dict:
        """Occupancy document (the ``fqdn_cache`` ResourceLedger row +
        ``status.fqdn``): live IP count, name count, high-water,
        bound-eviction total, and how many entries are already past
        expiry but not yet collected by the fqdn-gc tick."""
        if now is None:
            now = int(self.clock())
        with self._lock:
            pending = sum(
                1 for ent in self._entries.values()
                for exp in ent.values() if exp <= now)
            return {
                "ips": self._count,
                "names": len(self._entries),
                "high_water": self._high_water,
                "evictions": self._evictions,
                "pending_expiries": pending,
                "max_names": self.max_names,
                "max_ips_per_name": self.max_ips_per_name,
            }

    def lookup_selector(self, sel: FQDNSelector,
                        now: int = None) -> List[str]:
        """All live IPs whose name matches the selector (sorted)."""
        if now is None:
            now = int(self.clock())
        out = set()
        with self._lock:
            for name, ent in self._entries.items():
                if sel.matches(name):
                    out.update(ip for ip, exp in ent.items() if exp > now)
        return sorted(out)

    def names(self) -> List[Tuple[str, Dict[str, int]]]:
        with self._lock:
            return sorted((n, dict(e)) for n, e in self._entries.items())

    def __len__(self) -> int:
        with self._lock:
            return sum(len(e) for e in self._entries.values())

    # -- checkpoint (upstream persists the DNS cache for FQDN policy) -------
    def export_state(self) -> Dict:
        with self._lock:
            return {"now": int(self.clock()),
                    "entries": {n: dict(e)
                                for n, e in self._entries.items()}}

    def restore_state(self, state: Dict) -> None:
        # prune on restore: entries ALREADY expired when the checkpoint
        # was written must not resurrect — materialization filters them
        # anyway, but restored corpses would occupy the bounds and
        # re-expire through the next GC tick as phantom policy churn.
        # The cutoff is the EXPORTING cache's clock (carried in the
        # checkpoint): expiries are absolute in that clock's domain, and
        # comparing them against the restoring engine's (possibly wall)
        # clock would wrongly flush synthetic-clock checkpoints whole.
        cutoff = state.get("now")
        with self._lock:
            self._entries = {}
            self._count = 0
            for n, e in state.get("entries", {}).items():
                live = {ip: int(exp) for ip, exp in dict(e).items()
                        if cutoff is None or int(exp) > int(cutoff)}
                if live:
                    self._entries[normalize_name(n)] = live
                    self._count += len(live)
            if self._count > self._high_water:
                self._high_water = self._count
        self._notify()
