"""Host-side IPCache (analog of upstream ``pkg/ipcache`` + ``pkg/maps/ipcache``).

Maps IP prefixes → security identity ids. This host store is the source of
truth; the compiler lowers a snapshot of it into the stride-LPM tensor
(``cilium_tpu/compile/lpm.py``). Lookup misses resolve to ``reserved:world``,
matching the datapath's behavior (eps.h: no entry → WORLD_ID).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import normalize_prefix, parse_addr, parse_prefix


class IPCache:
    """prefix(canonical str) → identity id, with longest-prefix-match lookup."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: Dict[str, int] = {}
        self._revision = 0
        self._observers: List[Callable[[], None]] = []

    def add_observer(self, obs: Callable[[], None]) -> None:
        self._observers.append(obs)

    def _changed(self) -> None:
        self._revision += 1
        for obs in list(self._observers):
            obs()

    # -- mutation ------------------------------------------------------------
    def upsert(self, prefix: str, identity_id: int) -> None:
        with self._lock:
            key = normalize_prefix(prefix)
            if self._entries.get(key) == identity_id:
                return          # no-op upserts (e.g. a DNS TTL tick
                                # re-learning the same IPs) must not dirty
                                # the LPM or trigger regeneration
            self._entries[key] = identity_id
            self._changed()

    def delete(self, prefix: str) -> bool:
        with self._lock:
            ok = self._entries.pop(normalize_prefix(prefix), None) is not None
            if ok:
                self._changed()
            return ok

    # -- queries -------------------------------------------------------------
    @property
    def revision(self) -> int:
        return self._revision

    def snapshot(self) -> Dict[str, int]:
        """Copy of entries; the compiler's input."""
        with self._lock:
            return dict(self._entries)

    def get(self, prefix: str) -> Optional[int]:
        """Exact-prefix entry lookup (None if absent); NOT an LPM match."""
        with self._lock:
            return self._entries.get(normalize_prefix(prefix))

    def lookup(self, addr: str) -> int:
        """Host-side reference LPM lookup (slow; the device LPM tensor must
        agree with this exactly — the oracle uses it)."""
        with self._lock:
            return lpm_lookup(self._entries, addr)

    def __len__(self) -> int:
        return len(self._entries)


def lpm_lookup(entries: Dict[str, int], addr: str) -> int:
    """Longest-prefix-match over canonical prefix→id entries; miss → WORLD.

    IPv4 addresses only match IPv4 prefixes and IPv6 only IPv6 — upstream
    keeps two separate LPM maps (cilium_ipcache v4/v6), so ``::/0`` must not
    cover v4-mapped addresses. The device side mirrors this with two stride
    tries selected by the packet's family bit.
    """
    return lpm_lookup_pfx(entries, addr)[0]


def lpm_lookup_pfx(entries: Dict[str, int], addr: str
                   ) -> Tuple[int, Optional[str], int]:
    """LPM with match provenance: → (identity id, winning canonical prefix
    or None on miss, canonical prefix length or -1). The winning prefix is
    unique (two same-length prefixes covering one address are the same
    prefix), so this names exactly the entry whose slot the device trie's
    provenance plane carries (compile/lpm.py) — the oracle's half of the
    ``lpm_prefix`` bit-identity contract."""
    addr16, addr_is_v6 = parse_addr(addr)
    addr_int = int.from_bytes(addr16, "big")
    best_len = -1
    best_id = C.IDENTITY_WORLD
    best_pfx: Optional[str] = None
    for prefix, ident in entries.items():
        net16, plen, pfx_is_v6 = parse_prefix(prefix)
        if pfx_is_v6 != addr_is_v6:
            continue
        net_int = int.from_bytes(net16, "big")
        if plen == 0 or (addr_int >> (128 - plen)) == (net_int >> (128 - plen)):
            if plen > best_len:
                best_len = plen
                best_id = ident
                best_pfx = prefix
    return best_id, best_pfx, (best_len if best_pfx is not None else -1)
