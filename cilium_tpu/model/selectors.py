"""Endpoint selectors (analog of upstream ``pkg/policy/api.EndpointSelector``).

Supports k8s-style ``matchLabels`` and ``matchExpressions`` (In / NotIn /
Exists / DoesNotExist). Selector keys may carry an explicit source prefix
(``k8s:app``, ``reserved:world``, ``any:app``); bare keys default to ``any``,
matching the key under any label source — mirroring upstream's behavior of
prefixing CNP selector keys and treating ``any.`` as source-wildcard.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from cilium_tpu.model.labels import Labels, SOURCE_ANY


def _split_key(key: str) -> Tuple[str, str]:
    if ":" in key:
        source, k = key.split(":", 1)
        return source, k
    return SOURCE_ANY, key


@dataclass(frozen=True)
class MatchExpression:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()

    def matches(self, labels: Labels) -> bool:
        source, key = _split_key(self.key)
        lbls = labels.get_all(source, key)
        if self.operator == "In":
            return any(l.value in self.values for l in lbls)
        if self.operator == "NotIn":
            return all(l.value not in self.values for l in lbls)
        if self.operator == "Exists":
            return bool(lbls)
        if self.operator == "DoesNotExist":
            return not lbls
        raise ValueError(f"unknown matchExpressions operator {self.operator!r}")


@dataclass(frozen=True)
class EndpointSelector:
    """A label selector. The empty selector matches every endpoint/identity."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[MatchExpression, ...] = ()

    @classmethod
    def from_json(cls, obj: Optional[Dict]) -> "EndpointSelector":
        if obj is None:
            return cls()
        ml = tuple(sorted((k, v) for k, v in (obj.get("matchLabels") or {}).items()))
        mes: List[MatchExpression] = []
        for e in obj.get("matchExpressions") or []:
            mes.append(MatchExpression(
                key=e["key"],
                operator=e["operator"],
                values=tuple(e.get("values") or ()),
            ))
        return cls(match_labels=ml, match_expressions=tuple(mes))

    @classmethod
    def from_labels(cls, kv: Dict[str, str]) -> "EndpointSelector":
        return cls(match_labels=tuple(sorted(kv.items())))

    def matches(self, labels: Labels) -> bool:
        for key, want in self.match_labels:
            source, k = _split_key(key)
            if not any(l.value == want for l in labels.get_all(source, k)):
                return False
        for expr in self.match_expressions:
            if not expr.matches(labels):
                return False
        return True

    @property
    def is_wildcard(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def to_json(self) -> Dict:
        out: Dict = {}
        if self.match_labels:
            out["matchLabels"] = {k: v for k, v in self.match_labels}
        if self.match_expressions:
            out["matchExpressions"] = [
                {"key": e.key, "operator": e.operator,
                 **({"values": list(e.values)} if e.values else {})}
                for e in self.match_expressions
            ]
        return out

    def __str__(self) -> str:
        import json
        return json.dumps(self.to_json(), sort_keys=True)
