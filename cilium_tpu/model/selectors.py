"""Endpoint selectors (analog of upstream ``pkg/policy/api.EndpointSelector``).

Supports k8s-style ``matchLabels`` and ``matchExpressions`` (In / NotIn /
Exists / DoesNotExist). Selector keys may carry an explicit source prefix
(``k8s:app``, ``reserved:world``, ``any:app``); bare keys default to ``any``,
matching the key under any label source — mirroring upstream's behavior of
prefixing CNP selector keys and treating ``any.`` as source-wildcard.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from cilium_tpu.model.labels import Labels, SOURCE_ANY


def _split_key(key: str) -> Tuple[str, str]:
    if ":" in key:
        source, k = key.split(":", 1)
        return source, k
    return SOURCE_ANY, key


@dataclass(frozen=True)
class MatchExpression:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()

    def matches(self, labels: Labels) -> bool:
        source, key = _split_key(self.key)
        lbls = labels.get_all(source, key)
        if self.operator == "In":
            return any(l.value in self.values for l in lbls)
        if self.operator == "NotIn":
            return all(l.value not in self.values for l in lbls)
        if self.operator == "Exists":
            return bool(lbls)
        if self.operator == "DoesNotExist":
            return not lbls
        raise ValueError(f"unknown matchExpressions operator {self.operator!r}")


@dataclass(frozen=True)
class EndpointSelector:
    """A label selector. The empty selector matches every endpoint/identity."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[MatchExpression, ...] = ()

    _OPERATORS = ("In", "NotIn", "Exists", "DoesNotExist")

    @classmethod
    def from_json(cls, obj: Optional[Dict]) -> "EndpointSelector":
        """Strict parse: raises ValueError on malformed selectors (the rule
        parser converts to RuleParseError at its boundary) — hostile CNP
        documents must never escape as KeyError/TypeError (fuzz contract,
        tests/test_fuzz.py)."""
        if obj is None:
            return cls()
        if not isinstance(obj, dict):
            raise ValueError(f"selector must be an object, got "
                             f"{type(obj).__name__}")
        raw_ml = obj.get("matchLabels") or {}
        if not isinstance(raw_ml, dict):
            raise ValueError("matchLabels must be an object")
        for k, v in raw_ml.items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise ValueError("matchLabels keys/values must be strings")
        ml = tuple(sorted(raw_ml.items()))
        mes: List[MatchExpression] = []
        raw_mes = obj.get("matchExpressions") or []
        if not isinstance(raw_mes, (list, tuple)):
            raise ValueError("matchExpressions must be a list")
        for e in raw_mes:
            if not isinstance(e, dict):
                raise ValueError("matchExpressions entry must be an object")
            if "key" not in e or not isinstance(e["key"], str):
                raise ValueError("matchExpressions entry requires a "
                                 "string 'key'")
            op = e.get("operator")
            if op not in cls._OPERATORS:
                raise ValueError(f"unknown matchExpressions operator {op!r}")
            values = e.get("values") or ()
            if not all(isinstance(v, str) for v in values):
                raise ValueError("matchExpressions values must be strings")
            mes.append(MatchExpression(
                key=e["key"], operator=op, values=tuple(values)))
        return cls(match_labels=ml, match_expressions=tuple(mes))

    @classmethod
    def from_labels(cls, kv: Dict[str, str]) -> "EndpointSelector":
        return cls(match_labels=tuple(sorted(kv.items())))

    def matches(self, labels: Labels) -> bool:
        for key, want in self.match_labels:
            source, k = _split_key(key)
            if not any(l.value == want for l in labels.get_all(source, k)):
                return False
        for expr in self.match_expressions:
            if not expr.matches(labels):
                return False
        return True

    @property
    def is_wildcard(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def to_json(self) -> Dict:
        out: Dict = {}
        if self.match_labels:
            out["matchLabels"] = {k: v for k, v in self.match_labels}
        if self.match_expressions:
            out["matchExpressions"] = [
                {"key": e.key, "operator": e.operator,
                 **({"values": list(e.values)} if e.values else {})}
                for e in self.match_expressions
            ]
        return out

    def __str__(self) -> str:
        import json
        return json.dumps(self.to_json(), sort_keys=True)
