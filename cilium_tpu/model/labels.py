"""Label model (analog of upstream ``pkg/labels``).

A label is ``source:key=value``. Sources seen in practice: ``k8s``,
``reserved``, ``cidr``, ``unspec``; selectors may use source ``any`` to match a
key regardless of source. Identity is a function of the *sorted* label set, so
``Labels`` keeps a canonical sorted representation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, Optional, Tuple

SOURCE_ANY = "any"
SOURCE_K8S = "k8s"
SOURCE_RESERVED = "reserved"
SOURCE_CIDR = "cidr"
SOURCE_UNSPEC = "unspec"


@dataclass(frozen=True, order=True)
class Label:
    source: str
    key: str
    value: str = ""

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"

    @property
    def source_key(self) -> str:
        return f"{self.source}:{self.key}"


def parse_label(text: str, default_source: str = SOURCE_UNSPEC) -> Label:
    """Parse ``[source:]key[=value]``."""
    value = ""
    if "=" in text:
        text, value = text.split("=", 1)
    if ":" in text:
        source, key = text.split(":", 1)
    else:
        source, key = default_source, text
    return Label(source=source, key=key, value=value)


class Labels:
    """An immutable, canonically-sorted set of labels keyed by (source, key)."""

    __slots__ = ("_by_key", "_sorted", "_hash")

    def __init__(self, labels: Iterable[Label] = ()):
        by_key: Dict[Tuple[str, str], Label] = {}
        for lbl in labels:
            by_key[(lbl.source, lbl.key)] = lbl
        object.__setattr__(self, "_by_key", by_key)
        object.__setattr__(self, "_sorted", tuple(sorted(by_key.values())))
        object.__setattr__(self, "_hash", hash(self._sorted))

    # -- constructors -------------------------------------------------------
    @classmethod
    def parse(cls, texts: Iterable[str], default_source: str = SOURCE_UNSPEC) -> "Labels":
        return cls(parse_label(t, default_source) for t in texts)

    @classmethod
    def from_k8s(cls, kv: Dict[str, str]) -> "Labels":
        """Pod labels from a k8s-style dict; source forced to ``k8s``."""
        return cls(Label(SOURCE_K8S, k, v) for k, v in kv.items())

    @classmethod
    def reserved(cls, name: str) -> "Labels":
        return cls([Label(SOURCE_RESERVED, name)])

    # -- queries ------------------------------------------------------------
    def get(self, source: str, key: str) -> Optional[Label]:
        if source == SOURCE_ANY:
            # 'any' source: the key under any source (first in canonical order;
            # use get_all when several sources may carry the same key).
            matches = self.get_all(source, key)
            return matches[0] if matches else None
        return self._by_key.get((source, key))

    def get_all(self, source: str, key: str) -> Tuple[Label, ...]:
        """All labels matching (source, key); source 'any' spans sources."""
        if source == SOURCE_ANY:
            return tuple(l for l in self._sorted if l.key == key)
        lbl = self._by_key.get((source, key))
        return (lbl,) if lbl is not None else ()

    def has(self, source: str, key: str) -> bool:
        return self.get(source, key) is not None

    def sorted_list(self) -> Tuple[Label, ...]:
        return self._sorted

    def to_strings(self) -> Tuple[str, ...]:
        return tuple(str(lbl) for lbl in self._sorted)

    def union(self, other: "Labels") -> "Labels":
        return Labels(list(self._sorted) + list(other.sorted_list()))

    # -- dunder -------------------------------------------------------------
    def __iter__(self) -> Iterator[Label]:
        return iter(self._sorted)

    def __len__(self) -> int:
        return len(self._sorted)

    def __eq__(self, other) -> bool:
        return isinstance(other, Labels) and self._sorted == other._sorted

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        return f"Labels({', '.join(self.to_strings())})"
