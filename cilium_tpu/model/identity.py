"""Security-identity allocation (analog of upstream ``pkg/identity`` +
``pkg/allocator``).

- Reserved identities (host/world/...) are fixed small numbers.
- Cluster-scope identities (label-derived, for pods) are allocated from
  ``CLUSTER_IDENTITY_BASE`` upward, deterministically by first-allocation
  order, and are idempotent per label set (the single-node analog of the
  kvstore/CRD global allocator — SURVEY.md §3.5).
- Node-local identities (CIDR-derived) carry ``LOCAL_IDENTITY_SCOPE``
  (upstream: identity.IdentityScopeLocal == 1<<24).

Identities are *the tensor row space*: the compiler assigns each live
identity a dense row index; observers (SelectorCache) are notified on
allocate/release so MapState can be updated incrementally.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from cilium_tpu.model.labels import Label, Labels, SOURCE_CIDR, SOURCE_RESERVED
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import normalize_prefix


@dataclass(frozen=True)
class Identity:
    id: int
    labels: Labels

    @property
    def is_reserved(self) -> bool:
        return 0 < self.id < C.CLUSTER_IDENTITY_BASE

    @property
    def is_local(self) -> bool:
        return bool(self.id & C.LOCAL_IDENTITY_SCOPE)

    @property
    def is_world(self) -> bool:
        return self.id == C.IDENTITY_WORLD

    @property
    def is_cidr(self) -> bool:
        return any(l.source == SOURCE_CIDR for l in self.labels)

    def __repr__(self) -> str:
        return f"Identity({self.id}, {','.join(self.labels.to_strings())})"


def cidr_identity_labels(prefix: str) -> Labels:
    """Labels of a CIDR-derived identity.

    Includes one ``cidr:`` label for the prefix itself AND every *parent*
    prefix, plus ``reserved:world`` (CIDR identities are world-scoped). The
    parent labels are what make CIDR policy composition work: a rule allowing
    ``10.0.0.0/8`` compiles to a selector on label ``cidr:10.0.0.0/8``, and an
    IP that LPM-resolves to a *narrower* identity (say ``10.1.0.0/16``,
    created by some other rule) still matches because the /16 identity carries
    the /8 parent label — mirroring upstream's per-prefix-length CIDR labels.
    """
    import ipaddress
    net = ipaddress.ip_network(normalize_prefix(prefix), strict=False)
    labels: List[Label] = [Label(SOURCE_RESERVED, "world")]
    for plen in range(net.prefixlen, -1, -1):
        parent = net.supernet(new_prefix=plen) if plen < net.prefixlen else net
        labels.append(Label(SOURCE_CIDR, str(parent)))
    return Labels(labels)


# Observer signature: (added: [Identity], removed: [Identity]) -> None
IdentityObserver = Callable[[List[Identity], List[Identity]], None]


class IdentityAllocator:
    """Idempotent label-set → numeric identity allocator with observers."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_labels: Dict[Labels, Identity] = {}
        self._by_id: Dict[int, Identity] = {}
        self._refcount: Dict[int, int] = {}
        self._next_cluster = C.CLUSTER_IDENTITY_BASE
        self._next_local = C.LOCAL_IDENTITY_SCOPE
        self._observers: List[IdentityObserver] = []
        for name, num in C.RESERVED_IDENTITIES.items():
            if num == C.IDENTITY_UNKNOWN:
                continue
            ident = Identity(num, Labels.reserved(name))
            self._by_labels[ident.labels] = ident
            self._by_id[num] = ident
            self._refcount[num] = 1  # reserved identities are never released

    # -- observers ----------------------------------------------------------
    def add_observer(self, obs: IdentityObserver, replay: bool = True) -> None:
        with self._lock:
            self._observers.append(obs)
            if replay:
                obs(list(self._by_id.values()), [])

    def _notify(self, added: List[Identity], removed: List[Identity]) -> None:
        for obs in list(self._observers):
            obs(added, removed)

    # -- allocation ---------------------------------------------------------
    def allocate(self, labels: Labels) -> Identity:
        """Allocate (or ref) the identity for a label set."""
        with self._lock:
            existing = self._by_labels.get(labels)
            if existing is not None:
                self._refcount[existing.id] += 1
                return existing
            if any(l.source == SOURCE_CIDR for l in labels):
                num = self._next_local
                self._next_local += 1
            else:
                num = self._next_cluster
                self._next_cluster += 1
                if num > C.CLUSTER_IDENTITY_MAX:
                    raise RuntimeError("cluster identity space exhausted")
            ident = Identity(num, labels)
            self._by_labels[labels] = ident
            self._by_id[num] = ident
            self._refcount[num] = 1
            self._notify([ident], [])
            return ident

    def allocate_cidr(self, prefix: str) -> Identity:
        return self.allocate(cidr_identity_labels(prefix))

    def release(self, ident: Identity) -> bool:
        """Unref; returns True when the identity was fully removed."""
        with self._lock:
            if ident.id not in self._refcount or ident.is_reserved:
                return False
            self._refcount[ident.id] -= 1
            if self._refcount[ident.id] > 0:
                return False
            del self._refcount[ident.id]
            del self._by_id[ident.id]
            del self._by_labels[ident.labels]
            self._notify([], [ident])
            return True

    # -- queries ------------------------------------------------------------
    def get(self, num: int) -> Optional[Identity]:
        with self._lock:
            return self._by_id.get(num)

    def lookup_by_labels(self, labels: Labels) -> Optional[Identity]:
        with self._lock:
            return self._by_labels.get(labels)

    def all(self) -> List[Identity]:
        with self._lock:
            return sorted(self._by_id.values(), key=lambda i: i.id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_id)

    # -- persistence (checkpoint/resume: identity numbering must be stable) --
    def export_state(self) -> Dict:
        with self._lock:
            return {
                "next_cluster": self._next_cluster,
                "next_local": self._next_local,
                "identities": [
                    {"id": i.id, "labels": list(i.labels.to_strings()),
                     "refs": self._refcount[i.id]}
                    for i in self.all() if not i.is_reserved
                ],
            }

    def restore_state(self, state: Dict) -> None:
        with self._lock:
            added = []
            for ent in state["identities"]:
                labels = Labels.parse(ent["labels"])
                ident = Identity(ent["id"], labels)
                self._by_labels[labels] = ident
                self._by_id[ident.id] = ident
                self._refcount[ident.id] = ent.get("refs", 1)
                added.append(ident)
            self._next_cluster = state["next_cluster"]
            self._next_local = state["next_local"]
            if added:
                self._notify(added, [])
