"""CNP-compatible rule schema (analog of upstream ``pkg/policy/api``).

The JSON wire format deliberately follows CiliumNetworkPolicy's ``spec``
closely — ``endpointSelector``, ``ingress``/``egress`` (+ ``ingressDeny`` /
``egressDeny``), ``fromEndpoints``/``toEndpoints``, ``fromCIDR[Set]`` /
``toCIDR[Set]``, ``fromEntities``/``toEntities``, ``toPorts`` (with
``endPort`` ranges and L7 ``rules.http``), ``icmps`` — so that rule documents
written for upstream Cilium ingest unchanged (SURVEY.md §2: "Keep schema
~verbatim (JSON-compatible) for rule ingestion").

Out of scope v1 (parsed → rejected with a clear error rather than silently
ignored): ``fromRequires``/``toRequires``, L7 kafka/dns.
``toServices`` is accepted and resolved through a host-side service registry
(BASELINE config 3). ``toFQDNs`` is accepted and resolved through the DNS
cache (``model/fqdn.py``): learned IPs materialize as CIDR identities.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.model.labels import Labels
from cilium_tpu.model.selectors import EndpointSelector
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import normalize_prefix

ENTITY_NAMES = (
    "all", "world", "host", "remote-node", "cluster", "init", "health",
    "unmanaged", "kube-apiserver", "ingress",
)


class RuleParseError(ValueError):
    pass


# --------------------------------------------------------------------------- #
# L4 / L7
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class HTTPRule:
    """L7-lite HTTP rule: exact method (empty = any) + path *prefix*.

    Upstream's PortRuleHTTP.Path is a regex; the L7-lite contract (BASELINE
    config 4) reduces it to prefix matching on a tokenized header tensor.
    """
    method: str = ""
    path: str = ""

    def __post_init__(self):
        if self.method and self.method not in C.HTTP_METHOD_IDS:
            raise RuleParseError(f"unsupported HTTP method {self.method!r}")
        if len(self.path.encode()) > C.L7_PATH_MAXLEN:
            raise RuleParseError(
                f"path prefix longer than L7_PATH_MAXLEN={C.L7_PATH_MAXLEN}")


@dataclass(frozen=True)
class PortProtocol:
    """One port (or range) + protocol. ``port == 0`` → all ports of proto."""
    port: int = 0
    end_port: int = 0  # 0 → single port
    protocol: str = "ANY"  # TCP | UDP | SCTP | ANY | ICMP | ICMPv6

    def __post_init__(self):
        if self.protocol not in ("TCP", "UDP", "SCTP", "ANY", "ICMP", "ICMPv6"):
            raise RuleParseError(f"bad protocol {self.protocol!r}")
        if not (0 <= self.port <= 65535):
            raise RuleParseError(f"bad port {self.port}")
        if self.end_port:
            if self.port == 0:
                raise RuleParseError("endPort requires a non-zero port")
            if not (self.port <= self.end_port <= 65535):
                raise RuleParseError(
                    f"bad port range {self.port}-{self.end_port}")

    @property
    def port_range(self) -> Tuple[int, int]:
        """Inclusive (lo, hi); (0, 65535) when the port is wildcarded."""
        if self.port == 0:
            return (0, 65535)
        return (self.port, self.end_port or self.port)

    def protocols(self) -> Tuple[int, ...]:
        """Numeric protocols this PortProtocol expands to."""
        if self.protocol == "ANY":
            return C.PORT_PROTOS
        return (C.PROTO_BY_NAME[self.protocol],)


@dataclass(frozen=True)
class PortRule:
    ports: Tuple[PortProtocol, ...] = ()
    http: Tuple[HTTPRule, ...] = ()  # non-empty → L7 redirect semantics


@dataclass(frozen=True)
class ICMPField:
    family: str = "IPv4"  # IPv4 | IPv6
    icmp_type: int = 0

    def __post_init__(self):
        if self.family not in ("IPv4", "IPv6"):
            raise RuleParseError(f"bad ICMP family {self.family!r}")
        if not (0 <= self.icmp_type <= 255):
            raise RuleParseError(f"bad ICMP type {self.icmp_type}")


@dataclass(frozen=True)
class CIDRSelector:
    """A CIDR (+ optional excepts) peer selector."""
    cidr: str
    excepts: Tuple[str, ...] = ()

    def __post_init__(self):
        try:
            object.__setattr__(self, "cidr", normalize_prefix(self.cidr))
            object.__setattr__(
                self, "excepts", tuple(normalize_prefix(e) for e in self.excepts))
        except ValueError as e:
            raise RuleParseError(f"bad CIDR: {e}") from e


# --------------------------------------------------------------------------- #
# Rule blocks
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class PeerSpec:
    """The from*/to* side of one ingress/egress block."""
    endpoints: Tuple[EndpointSelector, ...] = ()
    cidrs: Tuple[CIDRSelector, ...] = ()
    entities: Tuple[str, ...] = ()
    services: Tuple[EndpointSelector, ...] = ()  # toServices k8s selectors
    fqdns: Tuple["FQDNSelector", ...] = ()       # toFQDNs DNS-name selectors

    @property
    def is_empty(self) -> bool:
        return not (self.endpoints or self.cidrs or self.entities
                    or self.services or self.fqdns)


@dataclass(frozen=True)
class RuleBlock:
    """One entry of ingress/egress/ingressDeny/egressDeny."""
    peer: PeerSpec = field(default_factory=PeerSpec)
    to_ports: Tuple[PortRule, ...] = ()
    icmps: Tuple[ICMPField, ...] = ()


@dataclass(frozen=True)
class Rule:
    endpoint_selector: EndpointSelector
    ingress: Tuple[RuleBlock, ...] = ()
    egress: Tuple[RuleBlock, ...] = ()
    ingress_deny: Tuple[RuleBlock, ...] = ()
    egress_deny: Tuple[RuleBlock, ...] = ()
    labels: Labels = field(default_factory=Labels)
    description: str = ""
    # Whether each section key was *present* in the source JSON — presence of
    # an (even empty) section flips default-enforcement for that direction,
    # exactly like upstream (a CNP with `ingress: []` default-denies ingress).
    has_ingress_section: bool = False
    has_egress_section: bool = False
    # The source JSON document (checkpoint/resume re-serializes from this).
    raw: Optional[Dict] = None

    def selects(self, ep_labels: Labels) -> bool:
        return self.endpoint_selector.matches(ep_labels)

    @property
    def enforces_ingress(self) -> bool:
        return self.has_ingress_section or bool(self.ingress or self.ingress_deny)

    @property
    def enforces_egress(self) -> bool:
        return self.has_egress_section or bool(self.egress or self.egress_deny)


# --------------------------------------------------------------------------- #
# Parsing
# --------------------------------------------------------------------------- #
_UNSUPPORTED_BLOCK_KEYS = {
    "fromRequires": "fromRequires is out of scope v1",
    "toRequires": "toRequires is out of scope v1",
}


def _parse_port_protocol(obj: Dict) -> PortProtocol:
    port_raw = obj.get("port", 0)
    try:
        port = int(port_raw) if port_raw not in (None, "") else 0
    except ValueError:
        raise RuleParseError(
            f"named ports are not supported (got port={port_raw!r})")
    try:
        end_port = int(obj.get("endPort", 0) or 0)
    except (TypeError, ValueError):
        raise RuleParseError(f"bad endPort {obj.get('endPort')!r}")
    return PortProtocol(
        port=port,
        end_port=end_port,
        protocol=obj.get("protocol", "ANY") or "ANY",
    )


def _parse_port_rule(obj: Dict) -> PortRule:
    ports = tuple(_parse_port_protocol(p) for p in obj.get("ports") or [])
    http: Tuple[HTTPRule, ...] = ()
    l7 = obj.get("rules") or {}
    for key in l7:
        if key == "http":
            http = tuple(
                HTTPRule(method=h.get("method", "") or "",
                         path=h.get("path", "") or "")
                for h in l7["http"] or []
            )
        else:
            raise RuleParseError(f"L7 rule kind {key!r} not supported (L7-lite is http-only)")
    return PortRule(ports=ports, http=http)


def _parse_block(obj: Dict, direction: str, deny: bool) -> RuleBlock:
    for bad, msg in _UNSUPPORTED_BLOCK_KEYS.items():
        if bad in obj:
            raise RuleParseError(msg)
    pfx = "from" if direction == "ingress" else "to"
    endpoints = tuple(EndpointSelector.from_json(s)
                      for s in obj.get(f"{pfx}Endpoints") or [])
    cidrs: List[CIDRSelector] = [CIDRSelector(cidr=c)
                                 for c in obj.get(f"{pfx}CIDR") or []]
    for cs in obj.get(f"{pfx}CIDRSet") or []:
        cidrs.append(CIDRSelector(cidr=cs["cidr"],
                                  excepts=tuple(cs.get("except") or ())))
    entities = tuple(obj.get(f"{pfx}Entities") or ())
    for ent in entities:
        if ent not in ENTITY_NAMES:
            raise RuleParseError(f"unknown entity {ent!r}")
    services: Tuple[EndpointSelector, ...] = ()
    if direction == "egress":
        svc_sels = []
        for svc in obj.get("toServices") or []:
            if "k8sServiceSelector" in svc:
                ks_sel = svc["k8sServiceSelector"]
                if "selector" not in ks_sel:
                    raise RuleParseError(
                        "toServices k8sServiceSelector requires a 'selector'")
                sel = EndpointSelector.from_json(ks_sel["selector"])
                if ks_sel.get("namespace"):
                    sel = EndpointSelector(
                        match_labels=sel.match_labels + (
                            ("k8s:io.kubernetes.service.namespace",
                             ks_sel["namespace"]),),
                        match_expressions=sel.match_expressions)
                svc_sels.append(sel)
            elif "k8sService" in svc:
                ks = svc["k8sService"]
                if not ks.get("serviceName"):
                    raise RuleParseError("toServices k8sService requires serviceName")
                svc_sels.append(EndpointSelector.from_labels({
                    "k8s:io.kubernetes.service.name": ks["serviceName"],
                    "k8s:io.kubernetes.service.namespace": ks.get("namespace", "default"),
                }))
            else:
                raise RuleParseError(
                    "toServices entry needs k8sService or k8sServiceSelector")
        services = tuple(svc_sels)
    fqdns: Tuple = ()
    if direction == "ingress" and obj.get("toFQDNs"):
        raise RuleParseError("toFQDNs is egress-only")
    if direction == "egress" and obj.get("toFQDNs"):
        if deny:
            # same restriction as upstream: FQDN peers are learn-as-you-go,
            # a deny that appears only after a DNS answer would be unsound
            raise RuleParseError("toFQDNs is not allowed in deny rules")
        from cilium_tpu.model.fqdn import FQDNSelector
        sels = []
        for f in obj["toFQDNs"]:
            try:
                sels.append(FQDNSelector(
                    match_name=f.get("matchName") or "",
                    match_pattern=f.get("matchPattern") or ""))
            except ValueError as e:
                raise RuleParseError(str(e)) from e
        fqdns = tuple(sels)
    to_ports = tuple(_parse_port_rule(p) for p in obj.get("toPorts") or [])
    icmps: List[ICMPField] = []
    for icmp_rule in obj.get("icmps") or []:
        for f in icmp_rule.get("fields") or []:
            if "type" not in f:
                raise RuleParseError("icmps field requires 'type'")
            try:
                icmp_type = int(f["type"])
            except (TypeError, ValueError):
                raise RuleParseError(f"bad ICMP type {f['type']!r}")
            icmps.append(ICMPField(family=f.get("family", "IPv4"),
                                   icmp_type=icmp_type))
    if deny:
        for pr in to_ports:
            if pr.http:
                raise RuleParseError("deny rules cannot carry L7 rules")
    return RuleBlock(
        peer=PeerSpec(endpoints=endpoints, cidrs=tuple(cidrs),
                      entities=entities, services=services, fqdns=fqdns),
        to_ports=to_ports,
        icmps=tuple(icmps),
    )


def parse_rule(obj: Dict) -> Rule:
    """Parse one CNP-style rule document. Total over JSON values: returns a
    Rule or raises RuleParseError — rule documents are an untrusted input
    path (upstream fuzzes pkg/policy/api for the same reason), so malformed
    shapes must never escape as KeyError/TypeError."""
    if not isinstance(obj, dict):
        raise RuleParseError(
            f"rule document must be an object, got {type(obj).__name__}")
    if "endpointSelector" not in obj:
        raise RuleParseError("rule missing endpointSelector")
    try:
        return _parse_rule_checked(obj)
    except RuleParseError:
        raise
    except (KeyError, TypeError, AttributeError, ValueError) as e:
        raise RuleParseError(f"malformed rule document: {e!r}") from e


def _parse_rule_checked(obj: Dict) -> Rule:
    return Rule(
        endpoint_selector=EndpointSelector.from_json(obj["endpointSelector"]),
        ingress=tuple(_parse_block(b, "ingress", False)
                      for b in obj.get("ingress") or []),
        egress=tuple(_parse_block(b, "egress", False)
                     for b in obj.get("egress") or []),
        ingress_deny=tuple(_parse_block(b, "ingress", True)
                           for b in obj.get("ingressDeny") or []),
        egress_deny=tuple(_parse_block(b, "egress", True)
                          for b in obj.get("egressDeny") or []),
        labels=Labels.parse(obj.get("labels") or []),
        description=obj.get("description", ""),
        has_ingress_section=("ingress" in obj or "ingressDeny" in obj),
        has_egress_section=("egress" in obj or "egressDeny" in obj),
        raw=obj,
    )


def parse_rules(docs: Sequence[Dict] | str) -> List[Rule]:
    """Parse a list of rule dicts, or a JSON string holding one."""
    if isinstance(docs, str):
        docs = json.loads(docs)
    if isinstance(docs, dict):
        docs = [docs]
    return [parse_rule(d) for d in docs]
