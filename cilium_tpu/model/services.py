"""Host-side service registry (thin analog of upstream ``pkg/service`` /
k8s Service watchers), just enough to resolve ``toServices`` rules
(BASELINE config 3): a service = name/namespace + labels + backend IPs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

from cilium_tpu.model.labels import Labels
from cilium_tpu.model.selectors import EndpointSelector


@dataclass(frozen=True)
class Service:
    name: str
    namespace: str
    backends: Tuple[str, ...]          # backend IPs (pod or external)
    extra_labels: Tuple[Tuple[str, str], ...] = ()

    @property
    def labels(self) -> Labels:
        base = {
            "k8s:io.kubernetes.service.name": self.name,
            "k8s:io.kubernetes.service.namespace": self.namespace,
        }
        base.update({k: v for k, v in self.extra_labels})
        return Labels.parse([f"{k}={v}" if v else k for k, v in base.items()])


class ServiceRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._services: Dict[Tuple[str, str], Service] = {}
        self._observers: List[Callable[[], None]] = []

    def add_observer(self, obs: Callable[[], None]) -> None:
        self._observers.append(obs)

    def upsert(self, svc: Service) -> None:
        with self._lock:
            self._services[(svc.namespace, svc.name)] = svc
        for obs in list(self._observers):
            obs()

    def delete(self, namespace: str, name: str) -> bool:
        with self._lock:
            ok = self._services.pop((namespace, name), None) is not None
        if ok:
            for obs in list(self._observers):
                obs()
        return ok

    def match(self, selector: EndpointSelector) -> List[Service]:
        with self._lock:
            return [svc for svc in self._services.values()
                    if selector.matches(svc.labels)]

    def all(self) -> List[Service]:
        with self._lock:
            return sorted(self._services.values(),
                          key=lambda s: (s.namespace, s.name))
