"""Host-side service registry (analog of upstream ``pkg/service`` /
``pkg/loadbalancer`` + the k8s Service watchers).

Two roles:
- resolve ``toServices`` rules (BASELINE config 3) via service labels →
  backend IPs;
- describe load-balancer state (frontends → backends) that
  ``compile/lb.py`` turns into the device service/Maglev/rev-NAT tensors
  (the lbmap analog, SURVEY.md §2 "Services/LB").

A frontend is a (VIP, port, proto) the datapath DNATs (ClusterIP,
NodePort on a node IP, ExternalIP). Backends are (ip, port, weight).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

from cilium_tpu.model.labels import Labels
from cilium_tpu.model.selectors import EndpointSelector

SVC_CLUSTER_IP = "ClusterIP"
SVC_NODEPORT = "NodePort"
SVC_EXTERNAL_IP = "ExternalIP"
SVC_LOADBALANCER = "LoadBalancer"


@dataclass(frozen=True)
class Frontend:
    """One DNAT'able service address: VIP:port/proto."""
    addr: str                          # v4 or v6 literal
    port: int
    proto: int = 6                     # IP protocol number (TCP)
    kind: str = SVC_CLUSTER_IP

    def __post_init__(self):
        if not (0 < self.port < 65536):
            raise ValueError(f"bad frontend port {self.port}")


@dataclass(frozen=True)
class Backend:
    addr: str
    port: int
    weight: int = 1                    # Maglev weighting (upstream lb.h)

    def __post_init__(self):
        if self.weight < 1:
            raise ValueError("backend weight must be >= 1")


@dataclass(frozen=True)
class Service:
    name: str
    namespace: str
    backends: Tuple[str, ...] = ()     # backend IPs for toServices expansion
    extra_labels: Tuple[Tuple[str, str], ...] = ()
    # Load-balancer state (empty for headless/selector-only services):
    frontends: Tuple[Frontend, ...] = ()
    lb_backends: Tuple[Backend, ...] = ()

    @property
    def backend_ips(self) -> Tuple[str, ...]:
        """IPs used for toServices rule expansion: explicit ``backends``
        else the LB backend addresses."""
        return self.backends or tuple(b.addr for b in self.lb_backends)

    @property
    def labels(self) -> Labels:
        base = {
            "k8s:io.kubernetes.service.name": self.name,
            "k8s:io.kubernetes.service.namespace": self.namespace,
        }
        base.update({k: v for k, v in self.extra_labels})
        return Labels.parse([f"{k}={v}" if v else k for k, v in base.items()])


class ServiceRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._services: Dict[Tuple[str, str], Service] = {}
        self._observers: List[Callable[[], None]] = []
        # Stable rev-NAT id per frontend (addr16, port, proto) — the analog
        # of upstream's allocated RevNatID: ids survive service churn so
        # long-lived CT entries never resolve to the wrong VIP. Ids are
        # never reused within a registry lifetime (stale CT entries could
        # otherwise rewrite replies to a NEW service's VIP).
        self._rnat_ids: Dict[Tuple[bytes, int, int], int] = {}
        self._next_rnat_id = 0
        # Frontend (addr16, port, proto) → owning (namespace, name): the
        # uniqueness index consulted at upsert time (O(frontends) per upsert,
        # not a scan of every registered service).
        self._fe_owner: Dict[Tuple[bytes, int, int], Tuple[str, str]] = {}
        self._revision = 0        # bumped on any LB-visible state change

    @property
    def revision(self) -> int:
        return self._revision

    def add_observer(self, obs: Callable[[], None]) -> None:
        self._observers.append(obs)

    def rnat_id(self, fe: Frontend) -> int:
        from cilium_tpu.utils.ip import parse_addr
        key = (parse_addr(fe.addr)[0], fe.port, fe.proto)
        with self._lock:
            rid = self._rnat_ids.get(key)
            if rid is None:
                rid = self._next_rnat_id
                self._next_rnat_id += 1
                self._rnat_ids[key] = rid
            return rid

    def export_rnat_state(self) -> Dict:
        from cilium_tpu.utils.ip import addr_to_str
        with self._lock:
            return {
                "next_id": self._next_rnat_id,
                "ids": [{"addr": addr_to_str(a), "port": p, "proto": pr,
                         "id": rid}
                        for (a, p, pr), rid in sorted(self._rnat_ids.items(),
                                                      key=lambda kv: kv[1])],
            }

    def restore_rnat_state(self, state: Dict) -> None:
        from cilium_tpu.utils.ip import parse_addr
        with self._lock:
            self._next_rnat_id = state["next_id"]
            self._rnat_ids = {
                (parse_addr(e["addr"])[0], e["port"], e["proto"]): e["id"]
                for e in state["ids"]}

    def upsert(self, svc: Service, validate: bool = True) -> None:
        """Register/replace a service. With ``validate`` (the default),
        frontend (VIP, port, proto) collisions with another service are
        rejected synchronously — deferring to snapshot-compile time would let
        the bad upsert poison every subsequent (auto-triggered) regeneration.
        ``validate=False`` is for checkpoint restore, which must accept
        whatever an older engine accepted (the conflict then surfaces at the
        next regenerate, logged + counted by the engine)."""
        from cilium_tpu.utils.ip import parse_addr
        me = (svc.namespace, svc.name)
        with self._lock:
            keys = [(parse_addr(fe.addr)[0], fe.port, fe.proto)
                    for fe in svc.frontends]
            if validate:
                seen = set()
                for key, fe in zip(keys, svc.frontends):
                    if key in seen:
                        raise ValueError(
                            f"service {svc.namespace}/{svc.name} declares "
                            f"frontend {fe.addr}:{fe.port}/{fe.proto} twice")
                    seen.add(key)
                    owner = self._fe_owner.get(key)
                    if owner is not None and owner != me:
                        raise ValueError(
                            f"frontend {fe.addr}:{fe.port}/{fe.proto} of "
                            f"service {svc.namespace}/{svc.name} conflicts "
                            f"with existing service {owner[0]}/{owner[1]}")
            old = self._services.get(me)
            freed = []
            if old is not None:
                for fe in old.frontends:
                    k = (parse_addr(fe.addr)[0], fe.port, fe.proto)
                    if self._fe_owner.get(k) == me and k not in keys:
                        del self._fe_owner[k]
                        freed.append(k)
            for key in keys:
                self._fe_owner.setdefault(key, me)
            for fe in svc.frontends:
                self.rnat_id(fe)      # allocate eagerly, deterministically
            self._services[me] = svc
            # a key this service no longer declares may have a shadowed
            # claimant (validate=False restores): hand ownership over so a
            # later validated upsert can't create an undetected live conflict
            for k in freed:
                self._reclaim_key(k)
            self._revision += 1
        for obs in list(self._observers):
            obs()

    def delete(self, namespace: str, name: str) -> bool:
        from cilium_tpu.utils.ip import parse_addr
        with self._lock:
            svc = self._services.pop((namespace, name), None)
            ok = svc is not None
            if ok:
                for fe in svc.frontends:
                    k = (parse_addr(fe.addr)[0], fe.port, fe.proto)
                    if self._fe_owner.get(k) == (namespace, name):
                        del self._fe_owner[k]
                        self._reclaim_key(k)
                self._revision += 1
        if ok:
            for obs in list(self._observers):
                obs()
        return ok

    def _reclaim_key(self, key: Tuple[bytes, int, int]) -> None:
        """After a frontend key loses its owner, re-own it to a surviving
        service still declaring it (deterministically: first in sorted
        (namespace, name) order). Without this, a conflicting service let in
        via ``validate=False`` stays shadowed with no owner recorded, and a
        third service could later claim the key with validation passing —
        an undetected live conflict. Caller holds the lock."""
        from cilium_tpu.utils.ip import parse_addr
        for me in sorted(self._services):
            for fe in self._services[me].frontends:
                if (parse_addr(fe.addr)[0], fe.port, fe.proto) == key:
                    self._fe_owner[key] = me
                    return

    def match(self, selector: EndpointSelector) -> List[Service]:
        with self._lock:
            return [svc for svc in self._services.values()
                    if selector.matches(svc.labels)]

    def all(self) -> List[Service]:
        with self._lock:
            return sorted(self._services.values(),
                          key=lambda s: (s.namespace, s.name))
