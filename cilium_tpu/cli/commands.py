"""CLI subcommand registry. Commands are added as subsystems land."""

from __future__ import annotations

import argparse
import json


def register(sub: "argparse._SubParsersAction") -> None:
    p_version = sub.add_parser("version", help="print framework version")
    p_version.set_defaults(func=_cmd_version)


def _cmd_version(args) -> int:
    import cilium_tpu
    print(json.dumps({"version": cilium_tpu.__version__}))
    return 0
