"""CLI subcommands (analog of upstream ``cilium-dbg``: endpoint/policy/
service/ct inspection + ``policy trace``, the parity debugging tool).

All inspection commands operate on a checkpoint state dir
(``--state-dir``, the /var/run/cilium analog) through
``checkpoint.load_host`` — pure host code, NO jax import, no device claim.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from cilium_tpu.utils import constants as C


def register(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser("version", help="print framework version")
    p.set_defaults(func=_cmd_version)

    p = sub.add_parser("status", help="agent state summary from a state dir")
    _add_state_dir(p)
    p.set_defaults(func=_cmd_status)

    p = sub.add_parser("endpoint", help="endpoint inspection")
    esub = p.add_subparsers(dest="subcmd", required=True)
    pl = esub.add_parser("list", help="list endpoints")
    _add_state_dir(pl)
    pl.set_defaults(func=_cmd_endpoint_list)
    pg = esub.add_parser("get", help="one endpoint incl. policy summary")
    _add_state_dir(pg)
    pg.add_argument("ep_id", type=int)
    pg.set_defaults(func=_cmd_endpoint_get)

    p = sub.add_parser("identity", help="identity inspection")
    isub = p.add_subparsers(dest="subcmd", required=True)
    il = isub.add_parser("list", help="list security identities")
    _add_state_dir(il)
    il.set_defaults(func=_cmd_identity_list)

    p = sub.add_parser("policy", help="policy inspection + trace")
    psub = p.add_subparsers(dest="subcmd", required=True)
    pg = psub.add_parser("get", help="dump the rule documents")
    _add_state_dir(pg)
    pg.set_defaults(func=_cmd_policy_get)
    pt = psub.add_parser(
        "trace", help="trace one (endpoint, flow) through the policy ladder "
        "(upstream: cilium policy trace)")
    _add_state_dir(pt)
    pt.add_argument("--ep", type=int, required=True, help="local endpoint id")
    pt.add_argument("--direction", choices=["egress", "ingress"],
                    default="egress")
    pt.add_argument("--remote", required=True,
                    help="remote IP (resolved via ipcache LPM)")
    pt.add_argument("--dport", type=int, required=True)
    pt.add_argument("--proto", default="TCP",
                    help="TCP|UDP|SCTP|ICMP|ICMPv6 or a number")
    pt.set_defaults(func=_cmd_policy_trace)

    p = sub.add_parser("service", help="service/LB inspection")
    ssub = p.add_subparsers(dest="subcmd", required=True)
    sl = ssub.add_parser("list", help="list services, frontends, backends")
    _add_state_dir(sl)
    sl.set_defaults(func=_cmd_service_list)

    p = sub.add_parser("fqdn", help="FQDN/DNS-cache inspection")
    fsub = p.add_subparsers(dest="subcmd", required=True)
    fc = fsub.add_parser("cache", help="list learned DNS names and IPs")
    _add_state_dir(fc)
    fc.set_defaults(func=_cmd_fqdn_cache)

    p = sub.add_parser("ct", help="conntrack inspection")
    csub = p.add_subparsers(dest="subcmd", required=True)
    cl = csub.add_parser("list", help="list live CT entries from ct.npz")
    _add_state_dir(cl)
    cl.add_argument("--now", type=int, default=None,
                    help="wall-clock for liveness (default: max created)")
    cl.add_argument("--limit", type=int, default=64)
    cl.set_defaults(func=_cmd_ct_list)

    p = sub.add_parser(
        "monitor", help="flow log viewer (cilium monitor / hubble observe)")
    p.add_argument("--flowlog-path",
                   help="JSONL sink written by the engine "
                        "(DaemonConfig.flowlog_path)")
    p.add_argument("--api", metavar="SOCKET",
                   help="live mode: read the in-memory flow ring of a "
                        "running engine over its REST socket")
    p.add_argument("--last", type=int, default=50)
    p.add_argument("--verdict", choices=["FORWARDED", "DROPPED"])
    p.add_argument("--endpoint", type=int)
    p.add_argument("--ip", help="match src or dst IP")
    p.add_argument("--port", type=int, help="match src or dst port")
    p.add_argument("--follow", "-f", action="store_true",
                   help="keep reading appended records (Ctrl-C to stop)")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="text")
    p.set_defaults(func=_cmd_monitor)

    p = sub.add_parser(
        "observe", help="vectorized filtered flow observe with match "
                        "provenance (hubble observe analog; "
                        "/v1/flows/observe)")
    p.add_argument("--api", metavar="SOCKET", required=True,
                   help="the running engine's REST socket (the observer "
                        "reads the in-memory columnar ring; there is no "
                        "offline mode — use `monitor` for the JSONL sink)")
    p.add_argument("--last", type=int, default=50,
                   help="one-shot: newest N matching records")
    p.add_argument("--verdict", choices=["FORWARDED", "DROPPED"])
    p.add_argument("--reason", help="drop reason name(s) or int(s), "
                                    "comma-separated (e.g. POLICY_DENY)")
    p.add_argument("--endpoint", help="local endpoint id(s)")
    p.add_argument("--identity", help="remote security identity id(s)")
    p.add_argument("--proto", help="protocol name(s)/number(s) (TCP,UDP,6)")
    p.add_argument("--port", help="src OR dst port(s)")
    p.add_argument("--sport", help="src port(s)")
    p.add_argument("--dport", help="dst port(s)")
    p.add_argument("--cidr", help="src OR dst address in CIDR(s)")
    p.add_argument("--src-cidr", dest="src_cidr")
    p.add_argument("--dst-cidr", dest="dst_cidr")
    p.add_argument("--rule", help="matched_rule coordinate(s) — show every "
                                  "flow a specific policy cell decided")
    p.add_argument("--direction", choices=["egress", "ingress"])
    p.add_argument("--not", dest="deny", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="denylist filter (repeatable): any observe param, "
                        "e.g. --not verdict=FORWARDED --not dport=53")
    p.add_argument("--follow", "-f", action="store_true",
                   help="seq-cursor streaming; ring wraparound surfaces "
                        "as an explicit gap record, never silent loss")
    p.add_argument("-o", "--output", choices=["compact", "json"],
                   default="compact",
                   help="compact: one line per flow with the 'because "
                        "rule R / prefix P / CT S' provenance rendering")
    p.set_defaults(func=_cmd_observe)

    p = sub.add_parser("metrics", help="print the Prometheus text file the "
                                       "engine exports; `metrics flows` "
                                       "shows the windowed flow-metrics "
                                       "time-series (hubble metrics analog)")
    p.add_argument("what", nargs="?", choices=["flows"],
                   help="'flows': windowed verdict/drop/proto/port/identity "
                        "series from /v1/flows/metrics (needs --api)")
    p.add_argument("--metrics-path",
                   help="DaemonConfig.metrics_path file")
    p.add_argument("--api", metavar="SOCKET",
                   help="live mode: scrape a running engine's REST socket")
    p.add_argument("--last", type=int, default=0,
                   help="flows mode: only the newest N windows")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="text")
    p.set_defaults(func=_cmd_metrics)

    p = sub.add_parser(
        "trace", help="sampled serving-path spans from a live agent: "
                      "per-stage p50/p99 summary + recent spans "
                      "(observe/trace.py; enable with "
                      "CILIUM_TPU_TRACE_SAMPLE_RATE)")
    p.add_argument("--api", metavar="SOCKET", required=True,
                   help="the running engine's REST socket (spans live "
                        "in-memory; there is no offline mode)")
    p.add_argument("--limit", type=int, default=20,
                   help="recent spans to fetch")
    p.add_argument("--name", help="filter spans by stage name "
                                  "(e.g. pipeline.dispatch)")
    p.add_argument("--spans", action="store_true",
                   help="print individual spans, not just the summary")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="text")
    p.set_defaults(func=_cmd_trace)

    p = sub.add_parser(
        "top", help="live resource-pressure view of a running agent "
                    "(observe/pressure.py ledger): one row per bounded "
                    "structure — occupancy bar, pressure, high-water, "
                    "time-to-exhaustion — plus the device HBM ledger. "
                    "Refreshes until interrupted; --once prints a single "
                    "frame (scriptable)")
    p.add_argument("--api", metavar="SOCKET", required=True,
                   help="the running engine's REST socket")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="text")
    p.set_defaults(func=_cmd_top)

    p = sub.add_parser(
        "debug-bundle",
        help="fetch the flight-recorder debug bundle from a live agent "
             "(observe/blackbox.py): the frozen anomaly bundle — parity "
             "mismatch, breaker open, watchdog restart, shed spike — or a "
             "live snapshot when nothing froze; carries the guard/regen "
             "event ring, verdict summaries, span tail, audit mismatch "
             "rows + revision, and live engine state")
    p.add_argument("--api", metavar="SOCKET", required=True,
                   help="the running engine's REST socket")
    p.add_argument("--out", metavar="FILE",
                   help="write the JSON bundle to FILE (default: stdout)")
    p.add_argument("--clear", action="store_true",
                   help="re-arm the recorder after the fetch (the next "
                        "anomaly freezes a fresh bundle)")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="json")
    p.set_defaults(func=_cmd_debug_bundle)

    p = sub.add_parser(
        "classify", help="serve one flow through a live agent's ingestion "
                         "pipeline (POST /v1/classify; the serving path "
                         "with guard semantics: 429 on overload shed, 503 "
                         "on breaker-open/hard-failed/timeout)")
    p.add_argument("--api", metavar="SOCKET", required=True)
    p.add_argument("--ep", type=int, required=True, help="local endpoint id")
    p.add_argument("--remote", required=True, help="remote IP")
    p.add_argument("--dport", type=int, required=True)
    p.add_argument("--sport", type=int, default=0)
    p.add_argument("--proto", default="TCP")
    p.add_argument("--direction", choices=["egress", "ingress"],
                   default="egress")
    p.add_argument("--src", help="source IP (default: the endpoint's "
                                 "first IP — required if it has none)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-submission staleness bound (shed past it)")
    p.add_argument("-o", "--output", choices=["text", "json"],
                   default="text")
    p.set_defaults(func=_cmd_classify)

    p = sub.add_parser(
        "verify", help="compile every datapath config combo and check the "
                       "memory budget (XLA-as-verifier; the test/verifier "
                       "CI-step analog)")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--max-hbm-bytes", type=int, default=None,
                   help="fail combos whose argument+temp memory exceeds this")
    p.add_argument("--quick", action="store_true",
                   help="skip the LB axis (faster pre-merge check)")
    p.add_argument("--report", metavar="FILE",
                   help="write the sweep + HBM budget summary as JSON "
                        "(embed into bench artifacts via --hbm-report so "
                        "offline verification and the live ledger cite "
                        "the same numbers)")
    p.set_defaults(func=_cmd_verify)

    p = sub.add_parser(
        "faults", help="fault injection: list points, arm/disarm on a live "
                       "agent, run the scripted chaos scenario")
    fsub = p.add_subparsers(dest="subcmd", required=True)
    fl = fsub.add_parser("list", help="list injection points (+ live stats "
                                      "with --api)")
    fl.add_argument("--api", metavar="SOCKET",
                    help="read fire/trip stats from a running agent")
    fl.add_argument("-o", "--output", choices=["text", "json"],
                    default="text")
    fl.set_defaults(func=_cmd_faults_list)
    fa = fsub.add_parser("arm", help="arm injection points on a live agent")
    fa.add_argument("--api", metavar="SOCKET", required=True)
    fa.add_argument("spec", help="CILIUM_TPU_FAULTS grammar, e.g. "
                                 "'regen.compile=fail:10'")
    fa.set_defaults(func=_cmd_faults_arm)
    fd = fsub.add_parser("disarm", help="disarm injection points on a live "
                                        "agent")
    fd.add_argument("--api", metavar="SOCKET", required=True)
    fd.add_argument("point", nargs="?", default="*",
                    help="point to disarm (default: all)")
    fd.set_defaults(func=_cmd_faults_disarm)
    fc = fsub.add_parser(
        "chaos", help="run the scripted chaos scenario and print the "
                      "verdict-continuity report (exit 1 on any classify "
                      "error or missed recovery). In-process mode runs "
                      "every phase (regen storm/recovery, peer flap, "
                      "pipeline dispatch storm, stall-storm watchdog "
                      "restart, circuit breaker open/probe/close, "
                      "checkpoint corruption); --api mode runs the regen "
                      "storm + recovery against the live agent only")
    fc.add_argument("--api", metavar="SOCKET",
                    help="target a running agent over its REST socket: "
                         "regen storm/recovery phases only (default: a "
                         "self-contained in-process engine, all phases)")
    fc.add_argument("--failures", type=int, default=10,
                    help="length of the regen.compile failure storm")
    fc.add_argument("--seed", type=int, default=7,
                    help="RNG seed for probabilistic fault phases")
    fc.add_argument("--datapath", choices=["jit", "fake"], default="jit",
                    help="in-process mode: device path (jit) or the "
                         "oracle-backed fake")
    fc.add_argument("-o", "--output", choices=["text", "json"],
                    default="text")
    fc.set_defaults(func=_cmd_faults_chaos)

    p = sub.add_parser(
        "map", help="compiled policy-map inspection (cilium bpf policy get)")
    msub = p.add_subparsers(dest="subcmd", required=True)
    mg = msub.add_parser("get", help="dump one endpoint's MapState entries")
    _add_state_dir(mg)
    mg.add_argument("--ep", type=int, required=True)
    mg.add_argument("--direction", choices=["egress", "ingress"],
                    default=None, help="default: both")
    mg.set_defaults(func=_cmd_map_get)

    p = sub.add_parser(
        "mesh", help="clustermesh inspection (cilium clustermesh status): "
                     "per-peer generation/lag, store reachability, "
                     "staleness verdict, conflicting prefix claims, "
                     "replication-lag p99 (runtime/clustermesh.py)")
    hsub = p.add_subparsers(dest="subcmd", required=True)
    hs = hsub.add_parser(
        "status", help="the mesh health/lag surface of a live agent "
                       "(the 'mesh' key of /v1/status)")
    hs.add_argument("--api", metavar="SOCKET", required=True,
                    help="the running engine's REST socket")
    hs.add_argument("-o", "--output", choices=["text", "json"],
                    default="text")
    hs.set_defaults(func=_cmd_mesh_status)


def _add_state_dir(p):
    p.add_argument("--state-dir",
                   help="checkpoint dir written by the engine "
                        "(the /var/run/cilium analog)")
    p.add_argument("--api", metavar="SOCKET",
                   help="live mode: query a running engine's REST API on "
                        "this unix socket instead of reading state files "
                        "(DaemonConfig.api_socket)")
    p.add_argument("-o", "--output", choices=["text", "json"], default="text")


def _load(args):
    if not getattr(args, "state_dir", None):
        raise SystemExit("one of --state-dir or --api is required")
    from cilium_tpu.runtime.checkpoint import load_host
    return load_host(args.state_dir)


def _live(args, method: str, path: str, body=None):
    """Fetch one route from a running engine (--api SOCKET live mode)."""
    from cilium_tpu.runtime.api import UnixAPIClient
    status, doc = UnixAPIClient(args.api).request(method, path, body)
    if status != 200:
        print(f"API error {status}: {doc}", file=sys.stderr)
        raise SystemExit(1)
    return doc


def _live_emit(args, method: str, path: str, body=None, text_fn=None) -> int:
    doc = _live(args, method, path, body)
    if args.output == "json" or text_fn is None:
        print(json.dumps(doc, indent=2, default=str))
    else:
        text_fn(doc)
    return 0


def _emit(args, doc, text_fn) -> int:
    if args.output == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        text_fn(doc)
    return 0


def _proto_num(text: str) -> int:
    if text.isdigit():
        return int(text)
    for num, name in C.PROTO_NAMES.items():
        if name.upper() == text.upper():
            return num
    raise SystemExit(f"unknown protocol {text!r}")


# --------------------------------------------------------------------------- #
def _cmd_version(args) -> int:
    import cilium_tpu
    print(json.dumps({"version": cilium_tpu.__version__}))
    return 0


def _cmd_status(args) -> int:
    def text(d):
        print(f"Policy revision:  {d['revision']}")
        print(f"Endpoints:        {d['endpoints']}")
        print(f"Identities:       {d['identities']}")
        print(f"Rules:            {d['rules']}")
        print(f"IPCache entries:  {d['ipcache_entries']}")
        print(f"Services:         {d['services']}")
        if d["conntrack"]:
            print(f"Conntrack:        {d['conntrack']['live']}/"
                  f"{d['conntrack']['capacity']} live")
        print(f"Enforcement:      {d['enforcement_mode']}")
        pl = d.get("pipeline")
        if pl:
            fl = pl.get("flush_reasons", {})
            br = pl.get("breaker") or {}
            print("Pipeline:")
            print(f"  state:          {pl.get('state', 'ok')}"
                  f" (breaker {br.get('state', 'closed')},"
                  f" restarts {pl.get('restarts', 0)}"
                  f"/{pl.get('max_restarts', '-')})")
            print(f"  queue depth:    {pl.get('queue_depth')}"
                  f" (inflight {pl.get('inflight')},"
                  f" staged rows {pl.get('staged_rows')})")
            print(f"  dispatched:     {pl.get('dispatched_batches')} batches"
                  f" ({pl.get('submitted')} submissions, fill"
                  f" {pl.get('fill_ratio_avg')})")
            print(f"  flush reasons:  "
                  + " ".join(f"{k}={v}" for k, v in sorted(fl.items())))
            print(f"  queue wait:     p50={pl.get('queue_wait_p50_ms')}ms"
                  f" p99={pl.get('queue_wait_p99_ms')}ms")
            print(f"  drops/faults:   {pl.get('admission_drops')} admission,"
                  f" {pl.get('dispatch_faults')} dispatch faults,"
                  f" {pl.get('dispatch_errors')} errors")
            shed = pl.get("shed_reasons") or {}
            if pl.get("shed_total") or pl.get("unavailable_total"):
                print(f"  shed:           {pl.get('shed_total', 0)} deadline ("
                      + " ".join(f"{k}={v}" for k, v in sorted(shed.items()))
                      + f"), {pl.get('unavailable_total', 0)} unavailable")
        at = d.get("autotune")
        if at:
            print(f"Autotune:         flush_ms={at.get('flush_ms')}"
                  f" min_bucket={at.get('min_bucket')}"
                  f" adjustments={at.get('adjustments_total')}")
        tr = d.get("trace")
        if tr and tr.get("enabled"):
            print(f"Tracing:          rate={tr.get('sample_rate')}"
                  f" sampled={tr.get('sampled_total')}"
                  f" ring={tr.get('spans_in_ring')}/{tr.get('capacity')}")

    if args.api:
        return _live_emit(args, "GET", "/v1/status", text_fn=text)
    st = _load(args)
    ct_doc = None
    if st.ct is not None:
        expiry = st.ct["expiry"]
        now = int(st.ct["created"].max()) if expiry.size else 0
        ct_doc = {"capacity": int(expiry.shape[0]),
                  "live": int((expiry > now).sum())}
    doc = {
        "revision": st.revision,
        "endpoints": len(st.endpoints),
        "identities": len(list(st.ctx.allocator.all())),
        "rules": len(st.repo),
        "ipcache_entries": len(st.ctx.ipcache.snapshot()),
        "services": len(st.ctx.services.all()),
        "conntrack": ct_doc,
        "enforcement_mode": st.ctx.enforcement_mode,
    }
    return _emit(args, doc, text)


def _cmd_endpoint_list(args) -> int:
    def text(d):
        for e in d:
            print(f"{e['ep_id']:<6} id={e['identity']:<8} "
                  f"ips={','.join(e['ips']) or '-':<24} "
                  f"labels={','.join(e['labels'])}")

    if args.api:
        return _live_emit(args, "GET", "/v1/endpoints", text_fn=text)
    st = _load(args)
    doc = [{"ep_id": ep.ep_id, "identity": ep.identity_id,
            "ips": list(ep.ips), "labels": list(ep.labels.to_strings()),
            "enforcement": ep.enforcement}
           for ep in sorted(st.endpoints.values(), key=lambda e: e.ep_id)]
    return _emit(args, doc, text)


def _cmd_endpoint_get(args) -> int:
    if args.api:
        return _live_emit(args, "GET", f"/v1/endpoints/{args.ep_id}")
    st = _load(args)
    ep = st.endpoints.get(args.ep_id)
    if ep is None:
        print(f"endpoint {args.ep_id} not found", file=sys.stderr)
        return 1
    pol = st.repo.resolve(ep)
    doc = {
        "ep_id": ep.ep_id, "identity": ep.identity_id,
        "ips": list(ep.ips), "labels": list(ep.labels.to_strings()),
        "enforcement": ep.enforcement,
        "policy_revision": pol.revision,
        "egress": {"enforced": pol.egress.enforced,
                   "entries": len(pol.egress.mapstate.items())},
        "ingress": {"enforced": pol.ingress.enforced,
                    "entries": len(pol.ingress.mapstate.items())},
    }
    return _emit(args, doc, lambda d: print(json.dumps(d, indent=2)))


def _cmd_identity_list(args) -> int:
    def text(d):
        for e in d:
            kind = ("reserved" if e["reserved"]
                    else "cidr" if e["local"] else "cluster")
            print(f"{e['id']:<10} {kind:<9} {','.join(e['labels'])}")

    if args.api:
        return _live_emit(args, "GET", "/v1/identities", text_fn=text)
    st = _load(args)
    doc = []
    for ident in st.ctx.allocator.all():
        doc.append({"id": ident.id,
                    "labels": list(ident.labels.to_strings()),
                    "reserved": ident.id < C.CLUSTER_IDENTITY_BASE,
                    "local": bool(ident.id & C.LOCAL_IDENTITY_SCOPE)})
    return _emit(args, doc, text)


def _cmd_policy_get(args) -> int:
    if args.api:
        return _live_emit(args, "GET", "/v1/policy")
    st = _load(args)
    doc = [r.raw for r in st.repo.all_rules() if r.raw is not None]
    return _emit(args, doc, lambda d: print(json.dumps(d, indent=2)))


def _key_str(key) -> str:
    ident = "ANY" if key.identity == C.IDENTITY_ANY else str(key.identity)
    proto = C.PROTO_NAMES.get(key.proto, str(key.proto))
    if key.is_port_wild:
        ports = "*"
    elif key.port_lo == key.port_hi:
        ports = str(key.port_lo)
    else:
        ports = f"{key.port_lo}-{key.port_hi}"
    return f"id={ident} proto={proto} port={ports}"


def _cmd_policy_trace(args) -> int:
    if args.api:
        return _live_emit(args, "POST", "/v1/policy/trace", body={
            "ep": args.ep, "direction": args.direction,
            "remote": args.remote, "dport": args.dport,
            "proto": args.proto})
    st = _load(args)
    ep = st.endpoints.get(args.ep)
    if ep is None:
        print(f"endpoint {args.ep} not found", file=sys.stderr)
        return 1
    from cilium_tpu.model.ipcache import lpm_lookup
    direction = C.DIR_EGRESS if args.direction == "egress" else C.DIR_INGRESS
    proto = _proto_num(args.proto)
    remote_id = lpm_lookup(st.ctx.ipcache.snapshot(), args.remote)
    pol = st.repo.resolve(ep)
    dirpol = pol.direction(direction)
    res = dirpol.lookup(remote_id, proto, args.dport) if dirpol.enforced \
        else None
    if not dirpol.enforced:
        verdict, reason = "ALLOWED", "direction not enforced (default mode)"
    elif res.decision == C.VERDICT_DENY:
        verdict, reason = "DENIED", "explicit deny rule"
    elif res.decision == C.VERDICT_MISS:
        verdict, reason = "DENIED", "no rule matched (default deny)"
    elif res.decision == C.VERDICT_REDIRECT:
        verdict = "ALLOWED"
        reason = "L7 redirect (http rules apply per request)"
    else:
        verdict, reason = "ALLOWED", "allow rule matched"
    doc = {
        "endpoint": ep.ep_id,
        "direction": args.direction,
        "remote": args.remote,
        "remote_identity": remote_id,
        "dport": args.dport,
        "proto": C.PROTO_NAMES.get(proto, str(proto)),
        "enforced": dirpol.enforced,
        "verdict": verdict,
        "reason": reason,
        "matched_key": _key_str(res.key)
        if res is not None and res.key is not None else None,
        "derived_from": list(res.entry.derived_from)
        if res is not None and res.entry is not None else [],
        "l7_rules": [repr(r) for r in sorted(res.entry.l7_rules, key=repr)]
        if res is not None and res.entry is not None
        and res.entry.l7_rules else [],
    }

    def text(d):
        print(f"Tracing {d['direction']} from endpoint {d['endpoint']} "
              f"to {d['remote']} (identity {d['remote_identity']}) "
              f"port {d['dport']}/{d['proto']}")
        print(f"  enforced:    {d['enforced']}")
        if d["matched_key"]:
            print(f"  matched key: {d['matched_key']}")
        for src in d["derived_from"]:
            print(f"    derived from: {src}")
        for r in d["l7_rules"]:
            print(f"    l7: {r}")
        print(f"Final verdict: {d['verdict']} ({d['reason']})")
    return _emit(args, doc, text)


def _cmd_service_list(args) -> int:
    if args.api:
        return _live_emit(args, "GET", "/v1/services")
    st = _load(args)
    doc = []
    for svc in st.ctx.services.all():
        doc.append({
            "name": f"{svc.namespace}/{svc.name}",
            "frontends": [f"{f.addr}:{f.port}/"
                          f"{C.PROTO_NAMES.get(f.proto, f.proto)} ({f.kind})"
                          for f in svc.frontends],
            "backends": [f"{b.addr}:{b.port} (w={b.weight})"
                         for b in svc.lb_backends] or list(svc.backends),
        })

    def text(d):
        for s in d:
            print(s["name"])
            for f in s["frontends"]:
                print(f"  frontend {f}")
            for b in s["backends"]:
                print(f"  backend  {b}")
    return _emit(args, doc, text)


def _cmd_fqdn_cache(args) -> int:
    if args.api:
        return _live_emit(args, "GET", "/v1/fqdn/cache")
    st = _load(args)
    doc = [{"name": name, "ips": {ip: exp for ip, exp in sorted(e.items())}}
           for name, e in st.ctx.fqdn_cache.names()]

    def text(d):
        for e in d:
            print(e["name"])
            for ip, exp in e["ips"].items():
                print(f"  {ip}  expires={exp}")
    return _emit(args, doc, text)


def _cmd_ct_list(args) -> int:
    if args.api:
        path = f"/v1/ct?limit={args.limit}"
        if args.now is not None:
            path += f"&now={args.now}"
        return _live_emit(args, "GET", path)
    import numpy as np
    from cilium_tpu.utils.ip import addr_to_str, words_to_addr
    st = _load(args)
    if st.ct is None:
        print("no ct.npz in state dir", file=sys.stderr)
        return 1
    keys = st.ct["keys"]
    expiry = st.ct["expiry"]
    now = args.now if args.now is not None else (
        int(st.ct["created"].max()) if expiry.size else 0)
    live = np.nonzero(expiry > now)[0]
    entries = []
    for slot in live[: args.limit]:
        w = keys[slot]
        entries.append({
            "src": addr_to_str(words_to_addr(w[0:4])),
            "dst": addr_to_str(words_to_addr(w[4:8])),
            "sport": int(w[8]) >> 16,
            "dport": int(w[8]) & 0xFFFF,
            "proto": C.PROTO_NAMES.get(int(w[9]) >> 8, str(int(w[9]) >> 8)),
            "dir": C.DIR_NAMES[int(w[9]) & 0xFF],
            "expires_in": int(expiry[slot]) - now,
            "pkts_fwd": int(st.ct["pkts_fwd"][slot]),
            "pkts_rev": int(st.ct["pkts_rev"][slot]),
            "rev_nat": int(st.ct["rev_nat"][slot])
            if "rev_nat" in st.ct else 0,
        })
    doc = {"live": int(live.size), "now": now, "entries": entries}

    def text(d):
        print(f"{d['live']} live entries (now={d['now']}):")
        for e in d["entries"]:
            rn = f" rnat={e['rev_nat']}" if e["rev_nat"] else ""
            print(f"  {e['proto']:<5} {e['src']}:{e['sport']} -> "
                  f"{e['dst']}:{e['dport']} [{e['dir']}] "
                  f"ttl={e['expires_in']}s fwd={e['pkts_fwd']} "
                  f"rev={e['pkts_rev']}{rn}")
    return _emit(args, doc, text)


def _flow_matches(r: dict, args) -> bool:
    if r.get("gap"):
        return True        # loss is always shown, filters never hide it
    if args.verdict and r.get("verdict") != args.verdict:
        return False
    if args.endpoint is not None and r.get("endpoint_id") != args.endpoint:
        return False
    if args.ip and args.ip not in (r.get("src_ip"), r.get("dst_ip")):
        return False
    if args.port is not None and args.port not in (r.get("src_port"),
                                                   r.get("dst_port")):
        return False
    return True


def _flow_line(r: dict) -> str:
    if r.get("gap"):
        return (f"** gap: {r['dropped']} records lost to ring wraparound "
                f"(resume at seq {r['resume_seq']}) **")
    mark = "->" if r.get("verdict") == "FORWARDED" else "xx"
    why = ("" if r.get("verdict") == "FORWARDED"
           else f" ({r.get('drop_reason_desc')})")
    return (f"[{r.get('time')}] ep{r.get('endpoint_id')} "
            f"{r.get('direction'):<7} {r.get('proto'):<5} "
            f"{r.get('src_ip')}:{r.get('src_port')} {mark} "
            f"{r.get('dst_ip')}:{r.get('dst_port')} "
            f"{r.get('ct_state'):<11} {r.get('verdict')}{why}")


def _cmd_monitor(args) -> int:
    import time as _time

    def emit(records):
        if args.output == "json":
            for r in records:
                print(json.dumps(r), flush=args.follow)
        else:
            for r in records:
                print(_flow_line(r), flush=args.follow)

    if args.api:
        from cilium_tpu.runtime.api import UnixAPIClient
        client = UnixAPIClient(args.api)
        qualifiers = ""
        if args.verdict:
            qualifiers += f"&verdict={args.verdict}"
        if args.endpoint is not None:
            qualifiers += f"&endpoint={args.endpoint}"
        status, records = client.get(f"/v1/flows?last={args.last}"
                                     + qualifiers)
        if status != 200:
            print(f"API error {status}: {records}", file=sys.stderr)
            return 1
        emit([r for r in records if _flow_matches(r, args)])
        if not args.follow:
            return 0
        # live follow: poll the seq cursor (hubble observe --follow analog)
        cursor = max((r.get("seq", 0) for r in records), default=0)
        try:
            while True:
                _time.sleep(0.3)
                status, fresh = client.get(
                    f"/v1/flows?since={cursor}" + qualifiers)
                if status != 200:
                    print(f"API error {status}: {fresh}", file=sys.stderr)
                    return 1
                if fresh:
                    # gap markers carry no seq; a filtered-empty page must
                    # still advance past the gap or the cursor would reset
                    # to 0 (a fresh attach) and disable future gap checks
                    new_cur = max((r["seq"] for r in fresh if "seq" in r),
                                  default=0)
                    for r in fresh:
                        if r.get("gap"):
                            new_cur = max(new_cur, r["resume_seq"] - 1)
                    cursor = max(cursor, new_cur)
                    emit([r for r in fresh if _flow_matches(r, args)])
        except KeyboardInterrupt:
            return 0
    if not args.flowlog_path:
        print("one of --flowlog-path or --api is required", file=sys.stderr)
        return 1
    if not os.path.exists(args.flowlog_path):
        print(f"no flow log at {args.flowlog_path}", file=sys.stderr)
        return 1

    with open(args.flowlog_path) as f:
        records = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if _flow_matches(r, args):
                records.append(r)
        emit(records[-args.last:])
        if not args.follow:
            return 0
        try:
            while True:
                line = f.readline()
                if not line:
                    _time.sleep(0.2)
                    continue
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if _flow_matches(r, args):
                    emit([r])
        except KeyboardInterrupt:
            return 0


#: observe CLI flags that map 1:1 onto /v1/flows/observe query params
_OBSERVE_PARAMS = ("verdict", "reason", "endpoint", "identity", "proto",
                   "port", "sport", "dport", "cidr", "src_cidr", "dst_cidr",
                   "rule", "direction")


def _observe_query(args) -> str:
    from urllib.parse import quote
    parts = []
    for name in _OBSERVE_PARAMS:
        val = getattr(args, name, None)
        if val is not None:
            parts.append(f"{name}={quote(str(val), safe='')}")
    for kv in args.deny:
        if "=" not in kv:
            raise ValueError(f"--not expects KEY=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        parts.append(f"not_{k}={quote(v, safe='')}")
    return "&".join(parts)


def _observe_line(r: dict, legend: dict) -> str:
    """The one-line 'verdict because rule R / prefix P / CT S' rendering:
    the flow plus the evidence behind its verdict, resolved through the
    legend the API attaches (explain=1)."""
    if r.get("gap"):
        return _flow_line(r)
    mr = int(r.get("matched_rule", -1))
    lp = int(r.get("lpm_prefix", -1))
    rinfo = legend.get("rules", {}).get(str(mr), {})
    pinfo = legend.get("prefixes", {}).get(str(lp), {})
    rule_s = (rinfo.get("label") or f"#{mr}") if mr >= 0 else "none"
    pfx_s = (pinfo.get("prefix") or f"#{lp}") if lp >= 0 else "miss(world)"
    return (f"{_flow_line(r)} because rule {rule_s} / prefix {pfx_s} "
            f"/ CT {r.get('ct_state_pre')}")


def _cmd_observe(args) -> int:
    import time as _time
    from cilium_tpu.runtime.api import UnixAPIClient
    client = UnixAPIClient(args.api)
    try:
        qualifiers = _observe_query(args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    base = "/v1/flows/observe?explain=1"
    if qualifiers:
        base += "&" + qualifiers

    def emit(doc):
        legend = doc.get("legend", {})
        records = ([doc["gap"]] if doc.get("gap") else []) + doc["flows"]
        for r in records:
            if args.output == "json":
                print(json.dumps(r), flush=args.follow)
            else:
                print(_observe_line(r, legend), flush=args.follow)

    status, doc = client.get(base + f"&last={args.last}")
    if status != 200:
        print(f"API error {status}: {doc}", file=sys.stderr)
        return 1
    emit(doc)
    if not args.follow:
        return 0
    # follow mode: seq-cursor polling; the server surfaces any wraparound
    # past the cursor as a structured gap record — loss is never silent
    cursor = doc["cursor"]
    try:
        while True:
            _time.sleep(0.3)
            status, doc = client.get(base + f"&since={cursor}")
            if status != 200:
                print(f"API error {status}: {doc}", file=sys.stderr)
                return 1
            cursor = doc["cursor"]
            if doc["flows"] or doc.get("gap"):
                emit(doc)
    except KeyboardInterrupt:
        return 0


def _flowmetrics_text(doc) -> None:
    for w in doc.get("windows", []):
        total = w["forwarded"] + w["dropped"]
        drops = " ".join(f"{k}={v}" for k, v in
                         sorted(w["drop_reasons"].items()))
        ports = ",".join(f"{p['port']}:{p['count']}"
                         for p in w["top_ports"][:5])
        print(f"[{w['window_start']}+{w['window_s']}s] "
              f"flows={total} fwd={w['forwarded']} drop={w['dropped']}"
              + (f" reasons[{drops}]" if drops else "")
              + (f" ports[{ports}]" if ports else ""))
    t = doc.get("totals", {})
    print(f"totals: fwd={t.get('forwarded')} drop={t.get('dropped')} "
          f"batches={t.get('batches')}")


def _cmd_metrics(args) -> int:
    if args.what == "flows":
        if not args.api:
            print("metrics flows reads the live windowed series; "
                  "--api SOCKET is required", file=sys.stderr)
            return 1
        path = "/v1/flows/metrics"
        if args.last:
            path += f"?last={args.last}"
        return _live_emit(args, "GET", path, text_fn=_flowmetrics_text)
    if args.output == "json":
        # the Prometheus exposition is text by definition; silently
        # handing unparseable text to a -o json caller would be worse
        print("-o json applies to `metrics flows`; the Prometheus "
              "exposition is text-only", file=sys.stderr)
        return 1
    if args.api:
        from cilium_tpu.runtime.api import UnixAPIClient
        status, text = UnixAPIClient(args.api).get("/v1/metrics")
        if status != 200:
            print(f"API error {status}: {text}", file=sys.stderr)
            return 1
        sys.stdout.write(text)
        return 0
    if not args.metrics_path:
        print("one of --metrics-path or --api is required", file=sys.stderr)
        return 1
    if not os.path.exists(args.metrics_path):
        print(f"no metrics file at {args.metrics_path}", file=sys.stderr)
        return 1
    with open(args.metrics_path) as f:
        sys.stdout.write(f.read())
    return 0


def _cmd_trace(args) -> int:
    path = f"/v1/trace?limit={args.limit}"
    if args.name:
        path += f"&name={args.name}"
    doc = _live(args, "GET", path)
    if args.output == "json":
        print(json.dumps(doc, indent=2, default=str))
        return 0
    st = doc.get("stats", {})
    if not st.get("enabled"):
        print("tracing is disabled (set trace_sample_rate, e.g. "
              "CILIUM_TPU_TRACE_SAMPLE_RATE=0.015625 for 1/64)")
    print(f"sampled={st.get('sampled_total')} "
          f"in_ring={st.get('spans_in_ring')}/{st.get('capacity')} "
          f"rate={st.get('sample_rate')} "
          f"dropped={st.get('spans_dropped_total', 0)} "
          f"wraps={st.get('ring_wraps', 0)}")
    if st.get("spans_dropped_total"):
        print(f"** {st['spans_dropped_total']} spans lost to ring "
              f"wraparound ({st.get('ring_wraps', 0)} full wraps) — the "
              "summary below covers only the surviving tail **")
    summary = doc.get("summary", {})
    if summary:
        print(f"{'stage':<24} {'count':>7} {'p50 ms':>10} {'p99 ms':>10} "
              f"{'max ms':>10}")
        for name, s in summary.items():
            print(f"{name:<24} {s['count']:>7} {s['p50_ms']:>10.3f} "
                  f"{s['p99_ms']:>10.3f} {s['max_ms']:>10.3f}")
    if args.spans:
        for sp in doc.get("spans", []):
            attrs = sp.get("attrs")
            print(f"  trace={sp['trace_id']:<8} {sp['name']:<24} "
                  f"{sp['duration_ms']:.3f}ms"
                  + (f" {attrs}" if attrs else ""))
    return 0


def _cmd_mesh_status(args) -> int:
    """Exit 0 on a healthy mesh, 1 when no mesh is attached, 2 when the
    mesh is MESH_STALE (scriptable: a monitoring probe can alert on it)."""
    doc = _live(args, "GET", "/v1/status")
    mesh = doc.get("mesh")
    if mesh is None:
        print("clustermesh is not attached (set cluster_store + "
              "node_name)", file=sys.stderr)
        return 1
    rc = 2 if mesh.get("state") == C.MESH_STALE else 0
    if args.output == "json":
        print(json.dumps(mesh, indent=2, default=str))
        return rc
    print(f"node={mesh['node']} generation={mesh['generation']} "
          f"state={mesh['state']} store_ok={mesh['store_ok']} "
          f"last_good_pass_age={mesh['last_good_pass_age_s']}s "
          f"budget={mesh['staleness_budget_s']}s")
    print(f"remote_entries={mesh['remote_entries']} "
          f"replication_lag_p99={mesh['replication_lag_p99_s']}s")
    peers = mesh.get("peers", {})
    if peers:
        print(f"{'peer':<24} {'generation':>10} {'entries':>8} "
              f"{'lag s':>9}")
        for name, pe in sorted(peers.items()):
            print(f"{name:<24} {pe['generation']:>10} "
                  f"{pe['entries']:>8} {pe['lag_s']:>9.3f}")
    else:
        print("no live peers")
    for prefix, conf in sorted(mesh.get("conflicts", {}).items()):
        print(f"conflict {prefix}: winner={conf['winner']} "
              f"losers={','.join(conf['losers'])}")
    return rc


def _cmd_debug_bundle(args) -> int:
    """Fetch (and optionally persist) the flight-recorder bundle. Exit 0
    always on a successful fetch — a live snapshot is a valid answer; the
    ``frozen`` field says whether an anomaly captured it."""
    path = "/v1/debug/bundle"
    if args.clear:
        path += "?clear=1"
    doc = _live(args, "GET", path)
    payload = json.dumps(doc, indent=2, default=str)
    state = (f"frozen: {doc.get('reason')}" if doc.get("frozen")
             else "live snapshot")
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        print(f"debug bundle ({state}) written to {args.out}")
        return 0
    if args.output == "text":
        print(f"bundle: {state} "
              f"(freezes_total={doc.get('freezes_total')})")
        for e in doc.get("events", [])[-20:]:
            attrs = {k: v for k, v in e.items()
                     if k not in ("t", "mono", "kind")}
            print(f"  [{e.get('t'):.3f}] {e.get('kind'):<16} {attrs}")
        eng = doc.get("engine", {})
        aud = eng.get("audit") or {}
        print(f"audit: checked={aud.get('checked_rows')} "
              f"mismatched={aud.get('mismatched_rows')} "
              f"skipped={aud.get('skipped_batches')}")
        return 0
    print(payload)
    return 0


def _cmd_classify(args) -> int:
    """The CLI serving path. Exit codes mirror the guard taxonomy: 0
    served, 2 overload shed (retry), 3 unavailable (back off), 1 other."""
    from cilium_tpu.runtime.api import UnixAPIClient
    src = args.src
    if src is None:
        status, ep = UnixAPIClient(args.api).get(f"/v1/endpoints/{args.ep}")
        if status != 200:
            print(f"API error {status}: {ep}", file=sys.stderr)
            return 1
        if not ep.get("ips"):
            print(f"endpoint {args.ep} has no IPs; pass --src",
                  file=sys.stderr)
            return 1
        src = ep["ips"][0]
    body = {"records": [{
        "src": src, "dst": args.remote, "sport": args.sport,
        "dport": args.dport, "proto": args.proto, "ep": args.ep,
        "direction": args.direction}]}
    if args.deadline_ms is not None:
        body["deadline_ms"] = args.deadline_ms
    status, doc = UnixAPIClient(args.api).post("/v1/classify", body)
    if args.output == "json":
        print(json.dumps({"status": status, **(doc if isinstance(doc, dict)
                                               else {"body": doc})},
                         indent=2, default=str))
    elif status == 200:
        v = doc["verdicts"][0]
        mark = "ALLOWED" if v["allow"] else "DENIED"
        print(f"{mark} {src}:{args.sport} -> {args.remote}:{args.dport} "
              f"({args.proto} {args.direction}) reason={v['reason']} "
              f"ct={v['ct_state']} remote_id={v['remote_identity']}")
    else:
        kind = doc.get("kind", "") if isinstance(doc, dict) else ""
        print(f"serving error {status} {kind}: "
              f"{doc.get('error', doc) if isinstance(doc, dict) else doc}",
              file=sys.stderr)
    if status == 200:
        return 0
    if status == 429:
        return 2
    if status == 503:
        return 3
    return 1


def _cmd_verify(args) -> int:
    import dataclasses
    from cilium_tpu.compile.verifier import budget_doc, verify_configs
    reports = verify_configs(batch=args.batch,
                             max_hbm_bytes=args.max_hbm_bytes,
                             quick=args.quick)
    bad = 0
    for r in reports:
        mem = (f"arg={r.argument_bytes} temp={r.temp_bytes} "
               f"out={r.output_bytes}" if r.ok else r.error)
        print(f"{'OK  ' if r.ok else 'FAIL'} {r.name:<24} {mem}")
        bad += not r.ok
    budget = budget_doc(reports, max_hbm_bytes=args.max_hbm_bytes)
    print(f"{len(reports) - bad}/{len(reports)} combos verifier-accepted")
    if budget["worst_combo"]:
        print(f"hbm budget: worst={budget['worst_combo']} "
              f"arg+temp={budget['worst_total_bytes']}"
              + (f" (budget {args.max_hbm_bytes})"
                 if args.max_hbm_bytes else ""))
    if getattr(args, "report", None):
        with open(args.report, "w") as f:
            json.dump({"budget": budget,
                       "reports": [dataclasses.asdict(r)
                                   for r in reports]}, f, indent=2)
        print(f"verify report written to {args.report}")
    return 1 if bad else 0


_BAR_W = 24


def _pressure_bar(pressure: float) -> str:
    filled = max(0, min(_BAR_W, int(round(pressure * _BAR_W))))
    return "[" + "#" * filled + "." * (_BAR_W - filled) + "]"


def _fmt_qty(v: float) -> str:
    """Compact quantity: 1.2M rows / 3.4G bytes read the same way."""
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.1f}{unit}"
    return f"{v:.0f}" if float(v).is_integer() else f"{v:.1f}"


def _fmt_eta(eta_s) -> str:
    if eta_s is None:
        return "-"
    if eta_s >= 3600:
        return f"{eta_s / 3600:.1f}h"
    if eta_s >= 60:
        return f"{eta_s / 60:.1f}m"
    return f"{eta_s:.0f}s"


def _top_frame(doc: dict) -> str:
    lines = [f"{'resource':<22} {'pressure':<{_BAR_W + 2}} {'occ':>8} "
             f"{'cap':>8} {'high':>8} {'eta':>7}  fc"]
    rows = doc.get("resources", {})
    order = sorted(rows, key=lambda r: -rows[r]["pressure"])
    for name in order:
        d = rows[name]
        lines.append(
            f"{name:<22} {_pressure_bar(d['pressure'])} "
            f"{_fmt_qty(d['occupancy']):>8} {_fmt_qty(d['capacity']):>8} "
            f"{_fmt_qty(d['high_water']):>8} {_fmt_eta(d['eta_s']):>7}  "
            f"{'!' if d.get('forecast') else ''}")
    lines.append(
        f"max_pressure={doc.get('max_pressure')} "
        f"pressured={','.join(doc.get('pressured', [])) or '-'} "
        f"forecasts={doc.get('forecasts_total', 0)} "
        f"polls={doc.get('polls_total', 0)}")
    hbm = (doc.get("hbm") or {}).get("ledger")
    if hbm:
        groups = " ".join(f"{k}={_fmt_qty(v)}B"
                          for k, v in sorted(hbm["groups"].items()) if v)
        lines.append(f"hbm: device={_fmt_qty(hbm['device_bytes'])}B "
                     f"({groups}) places={hbm['places_total']} "
                     f"patches={hbm['patches_total']}")
    return "\n".join(lines)


def _cmd_top(args) -> int:
    """The live capacity view (`cilium-tpu top`): one row per ledger
    resource, worst pressure first. Exit 0; --once makes it scriptable.
    Ctrl-C anywhere in the refresh loop (including mid-fetch against a
    slow agent) is the normal clean exit."""
    import time as _time
    try:
        while True:
            doc = _live(args, "GET", "/v1/resources")
            if args.output == "json":
                print(json.dumps(doc, indent=2, default=str))
            else:
                if not args.once:
                    sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
                print(_top_frame(doc))
            if args.once:
                return 0
            _time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def _cmd_map_get(args) -> int:
    if getattr(args, "api", None):
        print("map get reads compiled MapState detail from a checkpoint; "
              "use --state-dir (or `endpoint get --api` for live policy "
              "sizes)", file=sys.stderr)
        return 1
    st = _load(args)
    ep = st.endpoints.get(args.ep)
    if ep is None:
        print(f"endpoint {args.ep} not found", file=sys.stderr)
        return 1
    pol = st.repo.resolve(ep)
    directions = ([C.DIR_EGRESS, C.DIR_INGRESS] if args.direction is None
                  else [C.DIR_EGRESS if args.direction == "egress"
                        else C.DIR_INGRESS])
    doc = []
    for d in directions:
        dirpol = pol.direction(d)
        for key, entry in dirpol.mapstate.items():
            doc.append({
                "direction": C.DIR_NAMES[d],
                "key": _key_str(key),
                "action": ("DENY" if entry.deny
                           else "REDIRECT" if entry.is_redirect else "ALLOW"),
                "l7_rules": len(entry.l7_rules or ()),
                "derived_from": list(entry.derived_from),
            })

    def text(dl):
        for e in dl:
            l7 = f" l7={e['l7_rules']}" if e["l7_rules"] else ""
            print(f"{e['direction']:<8} {e['key']:<40} {e['action']}{l7}")
    return _emit(args, doc, text)


# --------------------------------------------------------------------------- #
# fault injection / chaos (runtime/faults.py — supervised degradation proof)
# --------------------------------------------------------------------------- #
def _cmd_faults_list(args) -> int:
    if args.api:
        doc = _live(args, "GET", "/v1/faults")
    else:
        # the local singleton: same schema as the live route, and it
        # reflects a CILIUM_TPU_FAULTS set in this process's environment
        from cilium_tpu.runtime.faults import FAULTS
        doc = FAULTS.stats()

    def text(d):
        for point in sorted(d):
            st = d[point]
            armed = f"armed={st.get('mode')}" if st.get("armed") else "idle"
            print(f"{point:<24} {armed:<12} fired={st.get('fired', 0):<6} "
                  f"trips={st.get('trips', 0):<6} {st.get('description', '')}")
    return _emit(args, doc, text)


def _cmd_faults_arm(args) -> int:
    doc = _live(args, "POST", "/v1/faults", {"spec": args.spec})
    print(json.dumps(doc))
    return 0


def _cmd_faults_disarm(args) -> int:
    doc = _live(args, "POST", "/v1/faults", {"disarm": args.point})
    print(json.dumps(doc))
    return 0


class _ChaosReport:
    """Phase-by-phase pass/fail accumulator for the chaos scenario."""

    def __init__(self):
        self.phases = []

    def record(self, phase: str, ok: bool, detail: str) -> bool:
        self.phases.append({"phase": phase, "ok": bool(ok), "detail": detail})
        return bool(ok)

    @property
    def ok(self) -> bool:
        return all(p["ok"] for p in self.phases)


_CHAOS_POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


def _chaos_inprocess(failures: int, seed: int, datapath_kind: str,
                     report: _ChaosReport) -> None:
    """Self-contained chaos scenario: build an engine, then prove verdict
    continuity under a regen failure storm, ipcache convergence under peer
    flaps, and cold-start fallback from a corrupted checkpoint."""
    import shutil
    import tempfile

    from cilium_tpu.kernels.records import batch_from_records
    from cilium_tpu.runtime import checkpoint as ckpt
    from cilium_tpu.runtime.clustermesh import ClusterMesh
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.engine import Engine
    from cilium_tpu.runtime.faults import FAULTS, FaultInjected
    from cilium_tpu.utils.ip import parse_addr
    from oracle import PacketRecord

    FAULTS.reset()

    def mk_engine():
        # guard knobs sized for the drill: quick breaker cooldown and
        # restart backoff; the stall timeout stays wide here (first
        # dispatches JIT-compile) and is shrunk at runtime for the
        # stall-storm phase, after the shapes are warm
        cfg = DaemonConfig(ct_capacity=4096, auto_regen=False,
                           pipeline_breaker_cooldown_s=0.4,
                           pipeline_max_restarts=5,
                           pipeline_restart_backoff_s=0.05)
        dp = None
        if datapath_kind == "fake":
            from cilium_tpu.runtime.datapath import FakeDatapath
            dp = FakeDatapath(cfg)
        return Engine(cfg, datapath=dp)

    def mk_batch(slot_of):
        s16, _ = parse_addr("192.168.1.10")
        recs = []
        for dst, dport in (("10.1.2.3", 443),    # allowed
                           ("10.1.2.3", 80),     # denied port
                           ("8.8.8.8", 443)):    # denied CIDR
            d16, _ = parse_addr(dst)
            recs.append(PacketRecord(s16, d16, 40000 + dport, dport,
                                     C.PROTO_TCP, C.TCP_SYN, False, 1,
                                     C.DIR_EGRESS))
        return batch_from_records(recs, slot_of)

    eng = mk_engine()
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(_CHAOS_POLICY)
    slot_of = eng.active.snapshot.ep_slot_of
    base = eng.classify(mk_batch(slot_of), now=100)
    baseline = [bool(a) for a in base["allow"]]

    # -- phase 1: regen.compile failure storm -------------------------------
    # every classify re-enters the failing compile (dirty engine) and must
    # still answer from the last-good snapshot, bit-identical to baseline
    FAULTS.arm("regen.compile", mode="fail", times=failures)
    classify_errors = divergences = 0
    for i in range(failures):
        eng._mark_dirty()                        # noqa: SLF001 — chaos driver
        try:
            out = eng.classify(mk_batch(slot_of), now=200 + i)
        except Exception:
            classify_errors += 1
            continue
        if [bool(a) for a in out["allow"]] != baseline:
            divergences += 1
    h = eng.health()
    report.record(
        "regen-storm",
        classify_errors == 0 and divergences == 0
        and h["state"] == C.HEALTH_DEGRADED
        and h["consecutive_regen_failures"] == failures,
        f"{failures} injected compile failures: {classify_errors} classify "
        f"errors, {divergences} verdict divergences, health={h['state']} "
        f"consecutive={h['consecutive_regen_failures']}")

    # -- phase 2: recovery --------------------------------------------------
    FAULTS.disarm("regen.compile")
    eng.regenerate(force=True)
    h = eng.health()
    report.record(
        "regen-recovery",
        h["state"] == C.HEALTH_OK
        and h["consecutive_regen_failures"] == 0,
        f"post-storm regenerate: health={h['state']} "
        f"consecutive={h['consecutive_regen_failures']}")

    # -- phase 3: clustermesh peer flap (+ skewed peer clock) ---------------
    store = tempfile.mkdtemp(prefix="cilium-tpu-chaos-mesh-")
    try:
        mesh = ClusterMesh(eng, store, "local", stale_after_s=300.0)
        peer = os.path.join(store, "peer1.json")

        def publish_peer(gen):
            doc = {"format_version": 1, "node": "peer1", "generation": gen,
                   "published_at": 0.0,          # peer clock wildly behind
                   "entries": {"10.99.0.5/32": {"labels": ["k8s:app=db"]}}}
            with open(peer + ".tmp", "w") as f:
                json.dump(doc, f)
            os.replace(peer + ".tmp", peer)

        publish_peer(1)
        mesh.sync()
        present0 = eng.ctx.ipcache.get("10.99.0.5/32") is not None
        FAULTS.arm("clustermesh.peer_read", mode="prob", prob=0.5, seed=seed)
        rounds, lost = 12, 0
        for gen in range(2, 2 + rounds):
            publish_peer(gen)
            mesh.sync()
            if eng.ctx.ipcache.get("10.99.0.5/32") is None:
                lost += 1
        FAULTS.disarm("clustermesh.peer_read")
        mesh.sync()
        present1 = eng.ctx.ipcache.get("10.99.0.5/32") is not None
        report.record(
            "peer-flap",
            present0 and present1 and lost == 0,
            f"{rounds} sync rounds at 50% peer-read failure (peer clock "
            f"skewed to epoch): entry lost in {lost} rounds, "
            f"converged={present1}")
    finally:
        shutil.rmtree(store, ignore_errors=True)

    # -- phase 3.5: pipeline dispatch storm ---------------------------------
    # pipelined ingestion under a 50% dispatch-fault storm: every submission
    # must still resolve, in order, with verdicts bit-identical to the
    # serial baseline (the scheduler retries trips — delay, never drop)
    FAULTS.arm("pipeline.dispatch", mode="prob", prob=0.5, seed=seed)
    n_sub = 24
    tickets = [eng.submit(mk_batch(slot_of), now=300 + i)
               for i in range(n_sub)]
    drained = eng.drain(timeout=60)
    pl_errors = pl_divergences = 0
    for t in tickets:
        try:
            out = t.result(timeout=5)
        except Exception:
            pl_errors += 1
            continue
        if [bool(a) for a in out["allow"]] != baseline:
            pl_divergences += 1
    FAULTS.disarm("pipeline.dispatch")
    pstats = eng.pipeline_stats() or {}
    report.record(
        "pipeline-storm",
        drained and pl_errors == 0 and pl_divergences == 0
        and pstats.get("dispatch_faults", 0) > 0,
        f"{n_sub} pipelined submissions at 50% dispatch faults: "
        f"{pstats.get('dispatch_faults', 0)} trips retried, {pl_errors} "
        f"errors, {pl_divergences} verdict divergences, drained={drained}")

    # -- phase 3.6: stall-storm → watchdog-supervised restart ---------------
    # a hang-mode fault wedges the worker inside dispatch (the device-stall
    # simulation); the watchdog must reject the wedged window, restart the
    # worker, and keep serving — post-restart verdicts bit-identical to
    # baseline, no ticket blocked forever
    pl = eng.start_pipeline()
    pl.set_stall_timeout_s(0.75)         # shapes are warm; stall fast
    FAULTS.arm("pipeline.dispatch", mode="hang", delay_s=4.0, times=1)
    tickets = [eng.submit(mk_batch(slot_of), now=500 + i) for i in range(8)]
    drained = eng.drain(timeout=30)
    FAULTS.disarm("pipeline.dispatch")   # release the fenced-off worker
    st_rejected = st_divergences = st_unresolved = 0
    for t in tickets:
        if not t.done():
            st_unresolved += 1
            continue
        try:
            out = t.result(timeout=1)
        except Exception:
            st_rejected += 1
            continue
        if [bool(a) for a in out["allow"]] != baseline:
            st_divergences += 1
    # post-restart serving: the fresh worker must answer bit-identical to
    # the serial baseline (give the restart backoff a moment to finish)
    import time as _t
    for _ in range(40):
        if (eng.pipeline_stats() or {}).get("state") == "ok":
            break
        _t.sleep(0.05)
    post_ok = 0
    for i in range(3):
        try:
            out = eng.submit(mk_batch(slot_of), now=550 + i).result(
                timeout=20)
            post_ok += [bool(a) for a in out["allow"]] == baseline
        except Exception:
            pass
    pstats = eng.pipeline_stats() or {}
    pl.set_stall_timeout_s(30.0)
    report.record(
        "stall-storm",
        drained and st_unresolved == 0 and st_rejected >= 1
        and st_divergences == 0 and pstats.get("restarts", 0) >= 1
        and post_ok == 3 and pstats.get("state") == "ok",
        f"hang-wedged dispatch: {pstats.get('restarts', 0)} watchdog "
        f"restart(s), {st_rejected} wedged tickets rejected, "
        f"{st_unresolved} stuck, {st_divergences} divergences, "
        f"{post_ok}/3 post-restart submissions matched baseline, "
        f"state={pstats.get('state')}")

    # -- phase 3.7: circuit breaker open → half-open probe → close ----------
    # fail-always dispatch: the first submission burns at most `threshold`
    # attempts before the breaker opens; subsequent submissions fail fast
    # (no retry burn); disarming + cooldown lets the half-open probe close
    # the breaker and serving resumes bit-identical
    from cilium_tpu.pipeline import PipelineUnavailable
    FAULTS.arm("pipeline.dispatch", mode="fail")
    faults_before = (eng.pipeline_stats() or {}).get("dispatch_faults", 0)
    first = eng.submit(mk_batch(slot_of), now=600)
    first_rejected = False
    try:
        first.result(timeout=20)
    except PipelineUnavailable:
        first_rejected = True
    except Exception:
        pass
    fast_fails = 0
    for i in range(3):                   # breaker open → instant rejection
        try:
            eng.submit(mk_batch(slot_of), now=601 + i)
        except PipelineUnavailable:
            fast_fails += 1
    pstats = eng.pipeline_stats() or {}
    opened = pstats.get("breaker", {}).get("state") == "open"
    burned = pstats.get("dispatch_faults", 0) - faults_before
    h_open = eng.health()
    FAULTS.disarm("pipeline.dispatch")
    _t.sleep(0.5)                        # past the 0.4s cooldown
    probe_ok = False
    try:
        out = eng.submit(mk_batch(slot_of), now=610).result(timeout=20)
        probe_ok = [bool(a) for a in out["allow"]] == baseline
    except Exception:
        pass
    pstats = eng.pipeline_stats() or {}
    report.record(
        "breaker",
        first_rejected and fast_fails == 3 and opened
        and burned <= eng.config.pipeline_breaker_threshold + 1
        and h_open["state"] != C.HEALTH_OK
        and probe_ok and pstats.get("breaker", {}).get("state") == "closed"
        and pstats.get("state") == "ok",
        f"fail-always dispatch: opened after {burned} attempts (cap "
        f"{eng.config.pipeline_breaker_threshold}), {fast_fails}/3 fast "
        f"fails, health={h_open['state']}, probe closed breaker and "
        f"matched baseline={probe_ok}")

    # -- phase 3.8: restart with CT survival (ROADMAP 3b) -------------------
    # an established flow must keep its verdict THROUGH a daemon restart:
    # checkpoint (versioned ct.npz), fresh engine, restore — the reply-side
    # packet classifies ESTABLISHED from the reloaded CT where a cold
    # engine would see NEW; the overlapped CT GC ticks cleanly after
    state = tempfile.mkdtemp(prefix="cilium-tpu-chaos-restart-")
    try:
        s16, _ = parse_addr("192.168.1.10")
        d16, _ = parse_addr("10.1.2.3")
        syn = PacketRecord(s16, d16, 45001, 443, C.PROTO_TCP, C.TCP_SYN,
                           False, 1, C.DIR_EGRESS)
        ack = PacketRecord(s16, d16, 45001, 443, C.PROTO_TCP, 0x10,
                           False, 1, C.DIR_EGRESS)
        b = batch_from_records([syn, ack], slot_of)
        out = eng.classify(b, now=700)
        established = bool(out["allow"][0]) and bool(out["allow"][1])
        ckpt.save(eng, state)
        fresh = mk_engine()
        restored = ckpt.restore(fresh, state)
        ct_kept = gc_ok = False
        if restored:
            b2 = batch_from_records([ack],
                                    fresh.active.snapshot.ep_slot_of)
            out2 = fresh.classify(b2, now=705)
            ct_kept = bool(out2["allow"][0]) and \
                int(out2["status"][0]) == int(C.CTStatus.ESTABLISHED)
            if hasattr(fresh.datapath, "sweep_step"):
                gc_ok = fresh.sweep_step(now=710) is not None \
                    and fresh.sweep_step(now=711) is not None
            else:
                fresh.sweep(now=710)
                gc_ok = True
        report.record(
            "ct-restart",
            established and restored is True and ct_kept and gc_ok,
            f"flow established={established}, restored={restored}, "
            f"reply ESTABLISHED through reloaded CT={ct_kept}, "
            f"post-restart GC tick ok={gc_ok}")
    finally:
        shutil.rmtree(state, ignore_errors=True)

    # -- phase 4: checkpoint torn write + corruption fallback ---------------
    state = tempfile.mkdtemp(prefix="cilium-tpu-chaos-ckpt-")
    try:
        FAULTS.arm("checkpoint.write", mode="fail", times=1)
        torn = False
        try:
            ckpt.save(eng, state)
        except FaultInjected:
            torn = True
        FAULTS.disarm("checkpoint.write")
        no_partial = not os.path.exists(os.path.join(state, "state.json"))
        ckpt.save(eng, state)                    # clean write
        with open(os.path.join(state, "state.json"), "r+") as f:
            f.write("{corrupt")                  # simulate torn write/bit rot
        fresh = mk_engine()
        restored = ckpt.restore(fresh, state)
        cold_ok = False
        if restored is False:                    # cold start must still work
            fresh.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",),
                               ep_id=1)
            fresh.apply_policy(_CHAOS_POLICY)
            out = fresh.classify(
                mk_batch(fresh.active.snapshot.ep_slot_of), now=400)
            cold_ok = [bool(a) for a in out["allow"]] == baseline
        report.record(
            "checkpoint-corruption",
            torn and no_partial and restored is False and cold_ok,
            f"torn save aborted cleanly={torn and no_partial}, corrupt "
            f"restore fell back to cold start={restored is False}, cold "
            f"engine verdicts match baseline={cold_ok}")
    finally:
        shutil.rmtree(state, ignore_errors=True)

    # -- phase 5: qos.enqueue fail-closed (multi-tenant QoS) ----------------
    # tenant classification at admission blows up: every faulted submission
    # must fail CLOSED onto the default tenant's FIFO budget — served,
    # never dropped, verdicts bit-identical — and the worker keeps running
    import numpy as np
    qcfg = DaemonConfig(ct_capacity=4096, auto_regen=False,
                        qos_enabled=True,
                        qos_tenants="gold=4:lane,bulk=1",
                        pipeline_max_restarts=5,
                        pipeline_restart_backoff_s=0.05)
    qdp = None
    if datapath_kind == "fake":
        from cilium_tpu.runtime.datapath import FakeDatapath
        qdp = FakeDatapath(qcfg)
    qeng = Engine(qcfg, datapath=qdp)
    qeng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    qeng.apply_policy(_CHAOS_POLICY)
    qslot = qeng.active.snapshot.ep_slot_of
    gold_tid = {v: k for k, v in qeng.qos.tenants().items()}["gold"]
    n_fault, n_sub = 4, 12
    FAULTS.arm("qos.enqueue", mode="fail", times=n_fault)
    qtickets = []
    for i in range(n_sub):
        qb = mk_batch(qslot)
        qb["_tenant"] = np.full(qb["valid"].shape, gold_tid,
                                dtype=np.int32)
        qtickets.append(qeng.submit(qb, now=800 + i))
    qdrained = qeng.drain(timeout=60)
    FAULTS.disarm("qos.enqueue")
    q_errors = q_div = 0
    for t in qtickets:
        try:
            out = t.result(timeout=5)
        except Exception:
            q_errors += 1
            continue
        if [bool(a) for a in out["allow"]] != baseline:
            q_div += 1
    failsafe = qeng.metrics.counters.get("qos_enqueue_failsafe_total", 0)
    fell = sum(1 for t in qtickets if t.tenant == "default")
    qstats = qeng.pipeline_stats() or {}
    report.record(
        "qos-enqueue-failsafe",
        qdrained and q_errors == 0 and q_div == 0
        and failsafe == n_fault and fell == n_fault
        and qstats.get("state") == "ok",
        f"{n_fault} injected classification faults over {n_sub} "
        f"submissions: {failsafe} fail-closed to the default tenant "
        f"({fell} tickets), {q_errors} errors, {q_div} verdict "
        f"divergences, state={qstats.get('state')}")

    # -- phase 6: dns-poison — fqdn.parse fail-open -------------------------
    # the in-band DNS learning tap's parser blows up mid-storm: every
    # faulted batch loses LEARNING only (counted in parse_errors), never
    # the reply — DNS verdicts stay bit-identical to the unfaulted
    # baseline, the cache stays empty while the fault is armed, and
    # learning resumes the moment the fault exhausts; a crafted
    # garbage-body frame afterwards is counted malformed and learns
    # nothing (the actual poisoning attempt)
    from cilium_tpu.fqdn.dnsparse import HEADER_LEN, encode_response
    from cilium_tpu.fqdn.proxy import DNSProxy

    dns_policy = _CHAOS_POLICY + [{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toCIDR": ["9.9.9.9/32"],
                    "toPorts": [{"ports": [{"port": "53",
                                            "protocol": "UDP"}],
                                 "rules": {"http": [{}]}}]}],
    }]
    deng = mk_engine()
    deng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    deng.apply_policy(dns_policy)
    dslot = deng.active.snapshot.ep_slot_of
    proxy = DNSProxy(deng.ctx.fqdn_cache, metrics=deng.metrics)
    good = encode_response("poison.example.com", ["10.7.7.7"], ttl=300)
    bad = bytearray(encode_response("poison.example.com", ["10.7.7.8"],
                                    ttl=300))
    bad[HEADER_LEN:] = b"\xff" * (len(bad) - HEADER_LEN)  # valid header,
    bad = bytes(bad)                                      # garbage body

    def dns_batch(frame):
        s16, _ = parse_addr("192.168.1.10")
        d16, _ = parse_addr("9.9.9.9")
        rec = PacketRecord(s16, d16, 41053, 53, C.PROTO_UDP, 0,
                           False, 1, C.DIR_EGRESS)
        b = batch_from_records([rec], dslot)
        nrow = b["valid"].shape[0]
        b["_dns_payload"] = np.zeros((nrow, 512), dtype=np.uint8)
        b["_dns_len"] = np.zeros((nrow,), dtype=np.int32)
        b["_dns_payload"][0, :len(frame)] = np.frombuffer(
            frame, dtype=np.uint8)
        b["_dns_len"][0] = len(frame)
        return b

    def tap(frame, now):
        b = dns_batch(frame)
        out = deng.classify(b, now=now)
        proxy.observe_batch(b, out)
        return out

    base = deng.classify(dns_batch(good), now=900)
    dns_baseline = [bool(a) for a in base["allow"]]
    redirect_seen = bool(np.asarray(base["redirect"]).any()) \
        and dns_baseline[0]
    n_fault = 3
    FAULTS.arm("fqdn.parse", mode="fail", times=n_fault)
    dns_div = 0
    for i in range(n_fault):
        out = tap(good, now=901 + i)
        if [bool(a) for a in out["allow"]] != dns_baseline:
            dns_div += 1
    FAULTS.disarm("fqdn.parse")
    errs_fault = proxy.parse_errors_total
    starved = len(deng.ctx.fqdn_cache) == 0       # fault cost learning
    tap(good, now=910)                            # fault gone: learning back
    recovered = len(deng.ctx.fqdn_cache) == 1 and proxy.observed_total == 1
    tap(bad, now=911)                             # the poison frame itself
    poison_rejected = len(deng.ctx.fqdn_cache) == 1 \
        and proxy.parse_errors_total == errs_fault + 1
    report.record(
        "dns-poison",
        redirect_seen and dns_div == 0 and errs_fault == n_fault
        and starved and recovered and poison_rejected,
        f"{n_fault} injected parse faults on the DNS tap: {errs_fault} "
        f"counted, {dns_div} verdict divergences, cache starved during "
        f"fault={starved}, learning resumed after={recovered}, garbage "
        f"frame counted malformed and learned nothing={poison_rejected}")


def _chaos_live(args, report: _ChaosReport) -> None:
    """Drive the chaos scenario against a running agent over its REST
    socket (arm via POST /v1/faults — the route is exempt from the
    ``api.handler`` point so the driver keeps control during the storm)."""
    from cilium_tpu.runtime.api import UnixAPIClient
    failures = args.failures
    client = UnixAPIClient(args.api)

    code, h0 = client.get("/v1/healthz")
    if not report.record("baseline",
                         code == 200 and h0.get("state") == C.HEALTH_OK,
                         f"healthz={code} state={h0.get('state')}"):
        return
    code, doc = client.post("/v1/faults",
                            {"spec": f"regen.compile=fail:{failures}"})
    if not report.record("arm", code == 200, f"arm regen.compile: {doc}"):
        return
    regen_errors = 0
    for _ in range(failures):
        code, _doc = client.post("/v1/regenerate")
        if code != 200:                          # degraded regen still
            regen_errors += 1                    # answers with last-good
    code_p, _probe = client.get("/v1/health")    # real classify continuity
    code, h1 = client.get("/v1/healthz")
    report.record(
        "regen-storm",
        regen_errors == 0 and code_p == 200 and code == 200
        and h1.get("state") in (C.HEALTH_DEGRADED, C.HEALTH_STALE)
        and h1.get("consecutive_regen_failures") == failures,
        f"{failures} forced regens: {regen_errors} API errors, datapath "
        f"probe={code_p}, health={h1.get('state')} "
        f"consecutive={h1.get('consecutive_regen_failures')}")
    client.post("/v1/faults", {"disarm": "*"})
    code, _doc = client.post("/v1/regenerate")
    code2, h2 = client.get("/v1/healthz")
    report.record(
        "regen-recovery",
        code == 200 and code2 == 200 and h2.get("state") == C.HEALTH_OK,
        f"post-storm regenerate={code}, health={h2.get('state')}")


def _cmd_faults_chaos(args) -> int:
    report = _ChaosReport()
    if args.api:
        _chaos_live(args, report)
    else:
        _chaos_inprocess(args.failures, args.seed, args.datapath, report)
    if args.output == "json":
        print(json.dumps({"ok": report.ok, "phases": report.phases},
                         indent=2))
    else:
        for p in report.phases:
            print(f"{'PASS' if p['ok'] else 'FAIL'} {p['phase']:<22} "
                  f"{p['detail']}")
        print("chaos scenario PASSED — verdict continuity held under all "
              "injected faults" if report.ok else "chaos scenario FAILED")
    return 0 if report.ok else 1
