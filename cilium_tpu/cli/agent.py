"""The long-lived agent process (upstream: the ``cilium-agent`` daemon,
SURVEY.md §3.1): construct the Engine, restore state, serve the REST API +
background controllers, checkpoint on shutdown.

    cilium-tpu agent run [--config FILE] [--state-dir DIR] [--api-socket S]
                         [--fake-datapath] ...

Startup mirrors §3.1's sequence: config population (file < env < flags) →
state restore (endpoints/rules/identities/CT re-placed from the state dir) →
regenerate (the restored-endpoints full build) → controllers + API up. On
SIGTERM/SIGINT: final checkpoint (the pinned-maps analog — flows survive the
restart), API socket removed, controllers stopped.
"""

from __future__ import annotations

import logging
import os
import signal
import threading


def register(sub) -> None:
    p = sub.add_parser("agent", help="run the long-lived agent daemon")
    asub = p.add_subparsers(dest="subcmd", required=True)
    pr = asub.add_parser("run", help="start the agent (blocks until SIGTERM)")
    pr.add_argument("--config", help="DaemonConfig JSON file")
    pr.add_argument("--api-socket", help="REST unix socket path "
                                         "(overrides config)")
    pr.add_argument("--state-dir", help="checkpoint dir (overrides config)")
    pr.add_argument("--fake-datapath", action="store_true",
                    help="serve with the oracle-backed fake (no jax/device; "
                         "control-plane testing)")
    pr.add_argument("--checkpoint-interval-s", type=float, default=60.0,
                    help="periodic checkpoint cadence (0 = only on exit)")
    pr.add_argument("--oneshot", action="store_true",
                    help="start, regenerate, checkpoint, exit (smoke runs)")
    pr.set_defaults(func=cmd_agent_run)


def cmd_agent_run(args) -> int:
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime import checkpoint as ckpt

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    log = logging.getLogger("cilium_tpu.agent")

    overrides = []
    if args.api_socket:
        overrides += ["--api-socket", args.api_socket]
    if args.state_dir:
        overrides += ["--state-dir", args.state_dir]
    config = DaemonConfig.load(config_file=args.config, argv=overrides)

    datapath = None
    if args.fake_datapath:
        from cilium_tpu.runtime.datapath import FakeDatapath
        datapath = FakeDatapath(config)
    from cilium_tpu.runtime.engine import Engine
    engine = Engine(config, datapath=datapath)

    state_dir = config.state_dir
    restored = False
    if state_dir and os.path.exists(os.path.join(state_dir, "state.json")):
        try:
            # a corrupt checkpoint returns False (cold start) — only an
            # unexpected error (bad engine state, device failure) raises
            restored = ckpt.restore(engine, state_dir)
        except Exception:
            log.exception("state restore failed; starting empty")
        if restored:
            log.info("restored state from %s (revision %d, %d endpoints)",
                     state_dir, engine.repo.revision, len(engine.endpoints))
        else:
            log.warning("checkpoint at %s unusable; cold start", state_dir)
    engine.regenerate(force=True)
    engine.start_background()
    if config.api_socket:
        log.info("api listening on %s", config.api_socket)

    def _checkpoint():
        if state_dir:
            ckpt.save(engine, state_dir)

    if state_dir and args.checkpoint_interval_s > 0:
        engine.controllers.update("checkpoint", _checkpoint,
                                  interval=args.checkpoint_interval_s)

    stop = threading.Event()

    def _on_signal(signum, _frame):
        log.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    log.info("agent up (revision %d, restored=%s, enforcement=%s)",
             engine.repo.revision, restored, engine.ctx.enforcement_mode)
    if args.oneshot:
        stop.set()
    stop.wait()

    try:
        _checkpoint()
        if state_dir:
            log.info("final checkpoint written to %s", state_dir)
    finally:
        engine.stop()
    return 0
