"""cilium-tpu CLI (analog of upstream ``cilium-dbg``).

Subcommands grow with the framework; ``trace`` is the policy-trace parity
debugging tool (upstream: ``cilium policy trace``).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cilium-tpu",
        description="TPU-native packet-classification framework CLI",
    )
    sub = parser.add_subparsers(dest="command")
    from cilium_tpu.cli import agent, commands
    commands.register(sub)
    agent.register(sub)
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 1
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
